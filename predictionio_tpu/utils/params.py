"""Engine-variant JSON -> typed params extraction (the JsonExtractor role).

The reference extracts per-component params from engine.json into typed Params
case classes via json4s/Gson (workflow/JsonExtractor.scala:39,
WorkflowUtils.extractParams:89).  Here params are plain dataclasses and one
codec suffices: dict -> dataclass with nested coercion, unknown-field
detection, and round-trip back to JSON for the engine-instance registry.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import typing
from typing import Any, Mapping, Type, TypeVar

T = TypeVar("T")


class ParamsError(ValueError):
    """Bad engine params JSON."""


class Params:
    """Marker base class for component parameters (controller/Params.scala:26)."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


def extract_params(cls: Type[T], payload: Mapping[str, Any] | None) -> T:
    """Build a params dataclass from a JSON object, coercing nested fields.

    A ``params_aliases`` classvar (dict json-name -> field-name) lets params
    classes accept the reference's JSON spellings (e.g. ``lambda`` -> ``reg``,
    which cannot be a Python field name).
    """
    payload = dict(payload or {})
    if not dataclasses.is_dataclass(cls):
        raise ParamsError(f"{cls!r} is not a dataclass params type")
    aliases, hints, fields, names = _class_info(cls)
    for json_name, field_name in aliases.items():
        if json_name in payload:
            payload[field_name] = payload.pop(json_name)
    unknown = set(payload) - names
    if unknown:
        raise ParamsError(
            f"unknown fields {sorted(unknown)} for {cls.__name__}; "
            f"expected a subset of {sorted(names)}"
        )
    kwargs: dict[str, Any] = {}
    for f in fields:
        if f.name in payload:
            kwargs[f.name] = _coerce(payload[f.name], hints.get(f.name), f.name)
        elif (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            raise ParamsError(f"missing required param {f.name!r} for {cls.__name__}")
    return cls(**kwargs)  # type: ignore[return-value]


@functools.lru_cache(maxsize=None)
def _class_info(cls):
    """Per-class introspection cache (type-hint resolution is ~40us; the
    serving hot path extracts a Query per request)."""
    fields = dataclasses.fields(cls)
    return (
        dict(getattr(cls, "params_aliases", {})),
        typing.get_type_hints(cls),
        fields,
        frozenset(f.name for f in fields),
    )


def _coerce(value: Any, typ: Any, name: str) -> Any:
    if typ is None or typ is Any:
        return value
    origin = typing.get_origin(typ)
    if origin is typing.Union:
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if value is None:
            return None
        if len(args) == 1:
            return _coerce(value, args[0], name)
        return value
    if origin in (list, tuple, set):
        args = typing.get_args(typ)
        elem = args[0] if args else Any
        if not isinstance(value, (list, tuple)):
            raise ParamsError(f"param {name!r}: expected list, got {value!r}")
        seq = [_coerce(v, elem, name) for v in value]
        return origin(seq) if origin is not list else seq
    if origin is dict:
        args = typing.get_args(typ)
        elem = args[1] if len(args) == 2 else Any
        return {k: _coerce(v, elem, name) for k, v in dict(value).items()}
    if dataclasses.is_dataclass(typ):
        if not isinstance(value, Mapping):
            raise ParamsError(f"param {name!r}: expected object for {typ.__name__}")
        return extract_params(typ, value)
    if typ is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParamsError(f"param {name!r}: expected number, got {value!r}")
        return float(value)
    if typ is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ParamsError(f"param {name!r}: expected int, got {value!r}")
        return value
    if typ is bool:
        if not isinstance(value, bool):
            raise ParamsError(f"param {name!r}: expected bool, got {value!r}")
        return value
    if typ is str:
        if not isinstance(value, str):
            raise ParamsError(f"param {name!r}: expected str, got {value!r}")
        return value
    return value


def params_to_dict(params: Any) -> dict[str, Any]:
    if params is None:
        return {}
    if dataclasses.is_dataclass(params):
        return dataclasses.asdict(params)
    if isinstance(params, Mapping):
        return dict(params)
    raise ParamsError(f"cannot serialize params {params!r}")


def params_to_json(params: Any) -> str:
    return json.dumps(params_to_dict(params), sort_keys=True)
