"""Component registries — the Python replacement for JVM reflection.

The reference instantiates DASE components, storage clients and engine
factories reflectively from class names (core/AbstractDoer.scala:45,
data/.../Storage.scala:310, workflow/WorkflowUtils.scala:47).  Here, components
register under a name (or are resolved by ``module:attr`` import path), and
``doer`` instantiates them with an optional params object — the AbstractDoer
contract: try ``Cls(params)``, fall back to ``Cls()``.
"""

from __future__ import annotations

import importlib
import inspect
from typing import Any, Callable, Generic, Iterator, TypeVar

T = TypeVar("T")


class Registry(Generic[T]):
    """A named registry with decorator-style registration and import-path fallback."""

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: dict[str, T] = {}

    def register(self, name: str, obj: T | None = None) -> Any:
        if obj is not None:
            self._entries[name] = obj
            return obj

        def deco(o: T) -> T:
            self._entries[name] = o
            return o

        return deco

    def get(self, name: str) -> T:
        """Resolve a registered name, or import ``pkg.module:attr`` / ``pkg.module.Attr``."""
        if name in self._entries:
            return self._entries[name]
        obj = resolve_import_path(name)
        if obj is None:
            raise KeyError(
                f"unknown {self.kind} {name!r}; registered: {sorted(self._entries)}"
            )
        return obj  # type: ignore[return-value]

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __iter__(self) -> Iterator[str]:
        return iter(self._entries)

    def names(self) -> list[str]:
        return sorted(self._entries)


def resolve_import_path(path: str) -> Any | None:
    """Import ``pkg.mod:attr`` or dotted ``pkg.mod.Attr``; None if unresolvable."""
    if ":" in path:
        mod_name, _, attr = path.partition(":")
        try:
            return getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError):
            return None
    if "." in path:
        mod_name, _, attr = path.rpartition(".")
        try:
            return getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError):
            return None
    return None


def _takes_argument(cls: Callable[..., Any]) -> bool:
    """True when cls's constructor accepts one positional argument."""
    try:
        sig = inspect.signature(cls)
    except (TypeError, ValueError):
        return True  # builtins without introspectable signatures: just try
    for p in sig.parameters.values():
        if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
            inspect.Parameter.VAR_POSITIONAL,
        ):
            return True
    return False


def doer(cls: Callable[..., T], params: Any = None) -> T:
    """Instantiate a component with params if its constructor takes them.

    Mirrors AbstractDoer (core/AbstractDoer.scala:45-67): prefer the
    one-argument ``(params)`` constructor, fall back to zero-argument.  The
    choice is made by signature inspection so a TypeError raised *inside* a
    matching constructor propagates instead of silently dropping the params.
    """
    if params is not None and _takes_argument(cls):
        return cls(params)  # type: ignore[call-arg]
    return cls()
