from predictionio_tpu.utils.registry import Registry

__all__ = ["Registry"]
