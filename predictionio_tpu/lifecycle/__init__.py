"""Zero-downtime model lifecycle: crash-safe generations, canary rollout,
and drift-triggered warm-start retraining (docs/robustness.md#model-lifecycle).
"""

from predictionio_tpu.lifecycle.canary import (
    CANARY_VARIANT,
    CanaryDecider,
    CanaryPolicy,
    CanaryTracker,
    in_canary_fraction,
)
from predictionio_tpu.lifecycle.controller import (
    LifecycleController,
    LifecyclePolicy,
    default_retrain,
)
from predictionio_tpu.lifecycle.generations import (
    CorruptModelError,
    Generation,
    GenerationStore,
    LifecycleError,
    compute_checksum,
    compute_checksums,
)

__all__ = [
    "CANARY_VARIANT",
    "CanaryDecider",
    "CanaryPolicy",
    "CanaryTracker",
    "CorruptModelError",
    "Generation",
    "GenerationStore",
    "LifecycleController",
    "LifecycleError",
    "LifecyclePolicy",
    "compute_checksum",
    "compute_checksums",
    "default_retrain",
    "in_canary_fraction",
]
