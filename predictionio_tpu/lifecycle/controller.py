"""The lifecycle controller: drift/staleness -> warm-start retrain ->
stage -> canary -> promote-or-rollback.

One controller runs per serving process (a daemon thread owned by the
prediction server when lifecycle is enabled).  Each :meth:`tick` is one
step of the closed loop:

1. **canary in progress** — evaluate the guardrails
   (:class:`~predictionio_tpu.lifecycle.canary.CanaryDecider`) against the
   request stats and the per-variant online metrics; promote or roll back
   when the verdict lands (both are one atomic manifest write followed by
   an in-memory generation flip + drain of the loser);
2. **idle** — when the :class:`~predictionio_tpu.obs.quality.DriftDetector`
   state is ``drifting``, or the live generation is older than
   ``staleness_s``, launch an incremental warm-start retrain from the
   event store (``run_train(warm_start_from=<live instance>)`` — ALS
   factors / NCF embedding tables of the previous generation seed the new
   run), checksum + stage the result, verify it, and start the canary.

Every transition is metered (``pio_lifecycle_*``) and every decision is
clock-injected so the chaos suite drives the loop deterministically with
``tick()`` under a frozen clock — no sleeps, no flakes.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from predictionio_tpu.lifecycle.canary import (
    CANARY_VARIANT,
    CONTINUE,
    PROMOTE,
    ROLLBACK,
    CanaryDecider,
    CanaryPolicy,
    CanaryTracker,
)
from predictionio_tpu.lifecycle.generations import (
    CorruptModelError,
    GenerationStore,
    LifecycleError,
)
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.resilience import faults

log = logging.getLogger("predictionio_tpu.lifecycle")

#: pio_lifecycle_state gauge values
IDLE, RETRAINING, CANARYING = 0, 1, 2


@dataclass(frozen=True)
class LifecyclePolicy:
    """Controller knobs on top of the canary policy."""

    canary: CanaryPolicy = CanaryPolicy()
    #: retrain when the live generation is older than this (None = never)
    staleness_s: float | None = None
    #: react to QualityMonitor drift state == "drifting"
    retrain_on_drift: bool = True
    #: minimum seconds between retrain launches (drift stays "drifting"
    #: for many windows; one reaction per episode, not one per tick)
    cooldown_s: float = 300.0
    #: controller thread wake interval
    check_interval_s: float = 5.0

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "LifecyclePolicy":
        """Policy from ``PIO_CANARY_*`` / ``PIO_LIFECYCLE_*`` env knobs
        (docs/robustness.md#model-lifecycle); unset keys keep defaults."""
        import os

        e = env if env is not None else os.environ
        canary = CanaryPolicy(
            fraction=float(e.get("PIO_CANARY_FRACTION", 0.1)),
            min_requests=int(e.get("PIO_CANARY_MIN_REQUESTS", 50)),
            max_error_rate=float(e.get("PIO_CANARY_MAX_ERROR_RATE", 0.05)),
            min_joined=int(e.get("PIO_CANARY_MIN_JOINED", 20)),
            metric=e.get("PIO_CANARY_METRIC", "hit_rate"),
            max_metric_regression=float(
                e.get("PIO_CANARY_MAX_REGRESSION", 0.10)
            ),
            max_canary_s=float(e.get("PIO_CANARY_MAX_S", 3600.0)),
        )
        staleness = e.get("PIO_LIFECYCLE_STALENESS_S")
        return cls(
            canary=canary,
            staleness_s=float(staleness) if staleness else None,
            retrain_on_drift=e.get(
                "PIO_LIFECYCLE_RETRAIN_ON_DRIFT", "1"
            ).lower() in ("1", "on", "true", "yes"),
            cooldown_s=float(e.get("PIO_LIFECYCLE_COOLDOWN_S", 300.0)),
            check_interval_s=float(e.get("PIO_LIFECYCLE_INTERVAL_S", 5.0)),
        )


class LifecycleController:
    """Closed-loop model lifecycle for one deployed engine."""

    def __init__(
        self,
        deployed: Any,  # server.prediction_server.DeployedEngine
        store: GenerationStore,
        quality: Any | None = None,  # obs.quality.QualityMonitor
        retrain: Callable[[str | None], Any] | None = None,
        policy: LifecyclePolicy | None = None,
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.deployed = deployed
        self.store = store
        self.quality = quality
        self.policy = policy or LifecyclePolicy()
        self._retrain = retrain
        self._clock = clock
        self.tracker = CanaryTracker(clock=clock)
        self.decider = CanaryDecider(self.policy.canary)
        self._lock = threading.RLock()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopping = False
        self._last_retrain_at: float | None = None
        self._last_event: dict[str, Any] | None = None
        reg = registry or REGISTRY
        self._m_state = reg.gauge(
            "pio_lifecycle_state",
            "Lifecycle controller state: 0 idle, 1 retraining, 2 canarying",
        )
        self._m_retrains = reg.counter(
            "pio_lifecycle_retrains_total",
            "Warm-start retrains launched, by trigger",
            labelnames=("trigger",),
        )
        self._m_retrain_failures = reg.counter(
            "pio_lifecycle_retrain_failures_total",
            "Retrain/stage attempts that failed before a canary started",
        )
        self._m_promotions = reg.counter(
            "pio_lifecycle_promotions_total",
            "Canary generations promoted to live",
        )
        self._m_rollbacks = reg.counter(
            "pio_lifecycle_rollbacks_total",
            "Generations rolled back, by reason",
            labelnames=("reason",),
        )
        self._m_corrupt = reg.counter(
            "pio_lifecycle_corrupt_blobs_total",
            "Model blobs refused by checksum verification",
        )
        self._m_age = reg.gauge(
            "pio_lifecycle_generation_age_seconds",
            "Age of the live generation",
        )
        self._m_state.set(IDLE)

    # -- introspection -------------------------------------------------------

    @property
    def last_event(self) -> dict[str, Any] | None:
        with self._lock:
            return dict(self._last_event) if self._last_event else None

    def _note(self, kind: str, **detail: Any) -> None:
        event = {"event": kind, "at": self._clock(), **detail}
        with self._lock:
            self._last_event = event
        log.info("lifecycle %s", kind, extra=detail)

    def snapshot(self) -> dict[str, Any]:
        """The /lifecycle.json controller half."""
        canary_gen = getattr(self.deployed, "canary_instance", None)
        return {
            "enabled": True,
            "canary_in_progress": canary_gen is not None,
            "canary_instance": getattr(canary_gen, "id", None),
            "canary_stats": self.tracker.snapshot(),
            "policy": {
                "fraction": self.policy.canary.fraction,
                "min_requests": self.policy.canary.min_requests,
                "max_error_rate": self.policy.canary.max_error_rate,
                "min_joined": self.policy.canary.min_joined,
                "metric": self.policy.canary.metric,
                "max_metric_regression":
                    self.policy.canary.max_metric_regression,
                "staleness_s": self.policy.staleness_s,
                "retrain_on_drift": self.policy.retrain_on_drift,
            },
            "last_event": self.last_event,
        }

    # -- the loop ------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="pio-lifecycle", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                self.tick()
            except Exception:
                log.exception("lifecycle tick failed")
            self._wake.wait(self.policy.check_interval_s)
            self._wake.clear()

    def tick(self) -> str | None:
        """One controller step; returns what happened (for tests/logs):
        None | "promote" | "rollback" | "retrain" | "retrain_failed"."""
        self._update_age_gauge()
        if getattr(self.deployed, "canary_instance", None) is not None:
            return self._tick_canary()
        trigger = self._should_retrain()
        if trigger is None:
            self._m_state.set(IDLE)
            return None
        return self._tick_retrain(trigger)

    def _update_age_gauge(self) -> None:
        live = self.store.live()
        if live is not None:
            anchor = live.promoted_at or live.created_at
            if anchor:
                self._m_age.set(max(self._clock() - anchor, 0.0))

    # -- canary evaluation ---------------------------------------------------

    def _tick_canary(self) -> str | None:
        self._m_state.set(CANARYING)
        comparison = None
        if self.quality is not None:
            comparison = self.quality.compare_variants(
                self.deployed.variant_label,
                CANARY_VARIANT,
                metric=self.policy.canary.metric,
            )
        verdict, reason = self.decider.evaluate(
            self.tracker.snapshot(), comparison, self.tracker.age_s()
        )
        if verdict == CONTINUE:
            return None
        canary = self.deployed.canary_instance
        if verdict == PROMOTE:
            self.promote(canary, reason)
            return PROMOTE
        self.rollback(canary, reason, label=_rollback_label(reason))
        return ROLLBACK

    def promote(self, instance: Any, reason: str = "") -> None:
        """Atomic flip to the canary generation: manifest commit first
        (the crash-safe point), then the in-memory swap, then the old
        generation drains."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.check("lifecycle.swap", f"promote {instance.id}")
        old = self.store.live()
        self.store.promote(instance.id, note=reason)
        self.deployed.promote_canary()
        self.tracker.stop()
        self._m_promotions.inc()
        self._m_state.set(IDLE)
        self._note(
            "promote", instance=instance.id, reason=reason,
            previous=getattr(old, "instance_id", None),
        )
        if old is not None:
            self.deployed.wait_drained(old.instance_id, timeout=5.0)

    def rollback(
        self, instance: Any, reason: str = "", label: str = "guardrail"
    ) -> None:
        """Abort the canary: manifest first, then drop the in-memory
        binding; live traffic never notices."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.check("lifecycle.swap", f"rollback {instance.id}")
        try:
            self.store.rollback(instance.id, note=reason)
        except LifecycleError:
            log.warning("rollback of unmanifested generation %s", instance.id)
        self.deployed.clear_canary()
        self.tracker.stop()
        self._m_rollbacks.labels(label).inc()
        self._m_state.set(IDLE)
        self._note("rollback", instance=instance.id, reason=reason)
        self.deployed.wait_drained(instance.id, timeout=5.0)

    # -- retrain trigger + launch -------------------------------------------

    def _should_retrain(self) -> str | None:
        now = self._clock()
        if (
            self._last_retrain_at is not None
            and now - self._last_retrain_at < self.policy.cooldown_s
        ):
            return None
        if (
            self.policy.retrain_on_drift
            and self.quality is not None
            and self.quality.drift_state() == "drifting"
        ):
            return "drift"
        if self.policy.staleness_s is not None:
            live = self.store.live()
            anchor = (
                (live.promoted_at or live.created_at) if live else None
            )
            if anchor and now - anchor > self.policy.staleness_s:
                return "stale"
        return None

    def _tick_retrain(self, trigger: str) -> str:
        self._m_state.set(RETRAINING)
        self._m_retrains.labels(trigger).inc()
        self._last_retrain_at = self._clock()
        live = self.store.live()
        warm_from = live.instance_id if live else None
        self._note("retrain", trigger=trigger, warm_start_from=warm_from)
        try:
            if faults.ACTIVE is not None:
                faults.ACTIVE.check("lifecycle.retrain", trigger)
            instance = self._run_retrain(warm_from)
            gen = self.store.record(instance.id, status="staged")
            self.store.verify(gen)
            self.deployed.stage_canary(
                instance, fraction=self.policy.canary.fraction
            )
            self.store.start_canary(instance.id)
            self.tracker.start()
        except CorruptModelError as e:
            self._m_corrupt.inc()
            return self._retrain_failed(trigger, e)
        except Exception as e:
            log.exception("warm-start retrain failed")
            return self._retrain_failed(trigger, e)
        self._m_state.set(CANARYING)
        self._note(
            "canary_started", instance=instance.id,
            fraction=self.policy.canary.fraction, trigger=trigger,
        )
        return "retrain"

    def _retrain_failed(self, trigger: str, error: Exception) -> str:
        """Unified failure path: whatever step died, no half-started
        canary may survive it — a binding staged before a later step
        failed would otherwise serve traffic un-tracked (no manifest
        entry, no started tracker, so the max_canary_s fail-safe could
        never fire)."""
        self.deployed.clear_canary()
        self.tracker.stop()
        self._m_retrain_failures.inc()
        self._m_state.set(IDLE)
        self._note("retrain_failed", trigger=trigger, error=str(error))
        return "retrain_failed"

    def _run_retrain(self, warm_start_from: str | None) -> Any:
        """Train a new generation; the default rebuilds the live
        instance's exact engine + params and warm-starts from its model."""
        if self._retrain is not None:
            return self._retrain(warm_start_from)
        return default_retrain(self.deployed, warm_start_from)


def _rollback_label(reason: str) -> str:
    """Map a decider reason to the pio_lifecycle_rollbacks_total{reason}
    label so dashboards can tell error-rate breaches, latency breaches,
    metric regressions, and evidence timeouts apart."""
    if "error rate" in reason:
        return "error_rate"
    if "p95" in reason:
        return "latency"
    if "regressed" in reason:
        return "metric_regression"
    if "burden of proof" in reason:
        return "timeout"
    return "guardrail"


def default_retrain(deployed: Any, warm_start_from: str | None) -> Any:
    """Retrain the deployed engine's live configuration from the event
    store, warm-starting from the previous generation's model.  Returns
    the COMPLETED EngineInstance."""
    from predictionio_tpu.core.base import EngineContext
    from predictionio_tpu.core.workflow import run_train

    instance = deployed.instance
    ctx = EngineContext(storage=deployed.storage, mode="train")
    return run_train(
        deployed.engine,
        deployed.params,
        ctx=ctx,
        engine_id=instance.engine_id,
        engine_version=instance.engine_version,
        engine_variant=instance.engine_variant,
        engine_factory=instance.engine_factory,
        storage=deployed.storage,
        warm_start_from=warm_start_from,
    )
