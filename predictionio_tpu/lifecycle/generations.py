"""Crash-safe model generation store: the manifest behind every swap.

A *generation* is one trained engine instance plus lifecycle bookkeeping:
its blob checksum, its status in the rollout state machine, and when it
was promoted.  One JSON manifest per engine (keyed by
``engine_id/engine_version/engine_variant``) records every generation this
engine has rolled through::

    staged ──> canary ──> live ──> retired
                  │
                  └─────> rolled_back

The manifest is stored THROUGH the Models backend (localfs / sqlite / s3 /
fsspec / remote), so it inherits each backend's atomic-visibility
primitive — the fsync'd tmp-write + ``os.replace`` on localfs
(data/storage/localfs_models.py), a transactional row on SQLite, an atomic
object PUT on S3.  Every manifest update is one whole-blob write: a crash
(SIGKILL included) between any two writes leaves the previous manifest
intact, so a restarting server always binds a *whole* generation — either
the old live or the new one, never a torn mix.

Checksums are SHA-256 over the stored model bytes (sharded manifest +
parts, or the legacy single blob).  ``verify`` recomputes and compares, so
a corrupt blob is refused at bind time and the binder falls back to the
most recent previously-live generation instead of crashing (or worse,
serving garbage).  The ``models.read`` fault seam lets the chaos suite
inject deterministic corruption here.

Pure stdlib; never touches a device.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from dataclasses import asdict, dataclass
from typing import Any

from predictionio_tpu.data.storage.base import (
    Models,
    _manifest_part_names,
)
from predictionio_tpu.obs.contention import ContendedLock
from predictionio_tpu.resilience import faults

log = logging.getLogger("predictionio_tpu.lifecycle")

#: manifest wire-format version
SCHEMA_VERSION = 1

#: rollout state machine statuses
STAGED, CANARY, LIVE, ROLLED_BACK, RETIRED = (
    "staged", "canary", "live", "rolled_back", "retired",
)
STATUSES = (STAGED, CANARY, LIVE, ROLLED_BACK, RETIRED)

#: storage key prefix for lifecycle manifests (instance ids are uuid hex,
#: so the prefix can never collide with a real model blob)
_MANIFEST_PREFIX = "__lifecycle__"


class LifecycleError(Exception):
    """Manifest-level failure (unknown generation, bad transition)."""


class CorruptModelError(LifecycleError):
    """Stored model bytes do not match the generation's checksum."""


def _now() -> float:
    """Wall clock for manifest timestamps — module-level so tests freeze it."""
    return time.time()


@dataclass
class Generation:
    """One row of the manifest."""

    instance_id: str
    checksum: str
    status: str = STAGED
    created_at: float = 0.0
    promoted_at: float | None = None
    rolled_back_at: float | None = None
    note: str = ""
    #: per-part SHA-256 over the sharded-checkpoint layout ("manifest" +
    #: one entry per named part) — verify() pinpoints WHICH factor shard
    #: went bad instead of just "bytes differ"; None for legacy single-blob
    part_checksums: dict[str, str] | None = None
    #: the serving ShardPlan (parallel.placement.ShardPlan.to_dict()) this
    #: generation was trained to serve under; deploy re-binds it onto the
    #: current mesh (re-sharding on device-count mismatch)
    shard_plan: dict[str, Any] | None = None

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Generation":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


def shard_axes(shard_plan: dict[str, Any] | None) -> dict[str, int] | None:
    """The mesh axes of a recorded serving plan (axis name -> size; -1 =
    all devices at bind time), or None for unsharded generations.  The
    compact identity a per-answer provenance record carries — the full
    plan (specs + real row counts) stays in the manifest."""
    if not shard_plan:
        return None
    axes = shard_plan.get("axes")
    return dict(axes) if axes else None


def compute_checksums(
    models_store: Models, instance_id: str
) -> tuple[str, dict[str, str] | None]:
    """One pass over the stored bytes of an engine instance's model:
    ``(whole_checksum, part_checksums)``.

    The whole checksum is SHA-256 over either layout (sharded manifest +
    parts, or the legacy single blob).  For the sharded layout the second
    element maps ``{"manifest": ..., "part:<name>": ...}`` to per-blob
    digests (one corrupt factor shard is named, not just detected); for the
    single-blob layout it is None.  Each blob is fetched ONCE — a multi-GB
    sharded checkpoint on a remote backend is not downloaded twice just to
    produce both granularities.

    Reads go through the ``models.read`` fault seam so chaos plans can
    corrupt bytes deterministically between write and verify.
    """
    h = hashlib.sha256()
    manifest = _read_blob(models_store, f"{instance_id}:manifest")
    if manifest is not None:
        h.update(b"manifest\x00")
        h.update(manifest)
        parts = {"manifest": hashlib.sha256(manifest).hexdigest()}
        for name in sorted(_manifest_part_names(manifest)):
            part = _read_blob(models_store, f"{instance_id}:part:{name}")
            if part is None:
                raise CorruptModelError(
                    f"model part {name!r} of instance {instance_id} is missing"
                )
            h.update(name.encode() + b"\x00")
            h.update(part)
            parts[f"part:{name}"] = hashlib.sha256(part).hexdigest()
        return h.hexdigest(), parts
    blob = _read_blob(models_store, instance_id)
    if blob is None:
        raise CorruptModelError(f"no model bytes for instance {instance_id}")
    h.update(b"blob\x00")
    h.update(blob)
    return h.hexdigest(), None


def compute_checksum(models_store: Models, instance_id: str) -> str:
    """Whole-model SHA-256 (either layout); see :func:`compute_checksums`."""
    return compute_checksums(models_store, instance_id)[0]


def compute_part_checksums(
    models_store: Models, instance_id: str
) -> dict[str, str] | None:
    """Per-part SHA-256 of a sharded checkpoint, or None for the legacy
    single-blob layout; see :func:`compute_checksums`."""
    return compute_checksums(models_store, instance_id)[1]


def _read_blob(models_store: Models, key: str) -> bytes | None:
    blob = models_store.get(key)
    if blob is not None and faults.ACTIVE is not None:
        blob = faults.ACTIVE.corrupt("models.read", key, blob)
    return blob


class GenerationStore:
    """The per-engine manifest: generation CRUD + the rollout transitions.

    Thread-safe within one process (all mutations under one lock); the
    commit point of every transition is a single whole-manifest write
    through the Models backend, so cross-process readers see either the
    previous or the next manifest, never a partial one.
    """

    def __init__(
        self,
        models_store: Models,
        engine_id: str = "default",
        engine_version: str = "default",
        engine_variant: str = "default",
        max_history: int = 32,
    ):
        self.models_store = models_store
        self.engine_id = engine_id
        self.engine_version = engine_version
        self.engine_variant = engine_variant
        self.max_history = max(max_history, 2)
        # manifest read-modify-write sections serialize here (reentrant:
        # transitions call read/write helpers under the same lock); metered
        # so a slow storage backend holding the manifest lock shows up as
        # pio_lock_wait_seconds{lock="generation_store"} on the other paths
        self._lock = ContendedLock("generation_store", reentrant=True)

    @property
    def engine_key(self) -> str:
        return f"{self.engine_id}/{self.engine_version}/{self.engine_variant}"

    @property
    def manifest_key(self) -> str:
        return f"{_MANIFEST_PREFIX}:{self.engine_key}"

    # -- manifest I/O --------------------------------------------------------

    def read(self) -> dict[str, Any]:
        raw = self.models_store.get(self.manifest_key)
        if raw is None:
            return {
                "schema": SCHEMA_VERSION,
                "engine": self.engine_key,
                "generations": [],
            }
        try:
            manifest = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise LifecycleError(
                f"lifecycle manifest for {self.engine_key} is unreadable: {e}"
            ) from e
        return manifest

    def _write(self, manifest: dict[str, Any]) -> None:
        gens = manifest["generations"]
        if len(gens) > self.max_history:
            # keep the tail (most recent) plus anything still active
            active = [
                g for g in gens[: -self.max_history]
                if g["status"] in (LIVE, CANARY)
            ]
            manifest["generations"] = active + gens[-self.max_history:]
        manifest["updated_at"] = _now()
        self.models_store.insert(
            self.manifest_key,
            json.dumps(manifest, sort_keys=True).encode("utf-8"),
        )

    def exists(self) -> bool:
        return self.models_store.get(self.manifest_key) is not None

    # -- queries -------------------------------------------------------------

    def generations(self) -> list[Generation]:
        return [
            Generation.from_dict(g) for g in self.read()["generations"]
        ]

    def get(self, instance_id: str) -> Generation | None:
        for g in self.generations():
            if g.instance_id == instance_id:
                return g
        return None

    def live(self) -> Generation | None:
        for g in reversed(self.generations()):
            if g.status == LIVE:
                return g
        return None

    def canary(self) -> Generation | None:
        for g in reversed(self.generations()):
            if g.status == CANARY:
                return g
        return None

    def bind_candidates(self) -> list[Generation]:
        """Generations a restarting server may bind, best first: the live
        one, then previously-live (retired) generations newest-first — the
        last-good fallback chain when a checksum refuses the head."""
        gens = self.generations()
        out = [g for g in reversed(gens) if g.status == LIVE]
        out.extend(g for g in reversed(gens) if g.status == RETIRED)
        return out

    # -- transitions (each one atomic manifest write) ------------------------

    def record(
        self,
        instance_id: str,
        status: str = STAGED,
        checksum: str | None = None,
        note: str = "",
        shard_plan: dict[str, Any] | None = None,
    ) -> Generation:
        """Add (or re-checksum) a generation.  Computes the blob checksum
        when not given — the staging step that makes later corruption
        detectable.  Sharded checkpoints additionally record PER-PART
        checksums (one corrupt factor shard is named, not just detected),
        and the generation embeds the model's ShardPlan (given explicitly
        or read from the ``run_train`` sidecar) so the manifest is the
        durable record of how a sharded model was laid out."""
        if status not in STATUSES:
            raise LifecycleError(f"unknown generation status {status!r}")
        with self._lock:
            part_checksums = None
            if checksum is None:
                checksum, part_checksums = compute_checksums(
                    self.models_store, instance_id
                )
            if shard_plan is None:
                from predictionio_tpu.core.workflow import read_shard_plan

                shard_plan = read_shard_plan(self.models_store, instance_id)
            manifest = self.read()
            now = _now()
            entry = Generation(
                instance_id=instance_id,
                checksum=checksum,
                status=status,
                created_at=now,
                promoted_at=now if status == LIVE else None,
                part_checksums=part_checksums,
                shard_plan=shard_plan,
            )
            if note:
                entry.note = note
            gens = [
                g for g in manifest["generations"]
                if g["instance_id"] != instance_id
            ]
            if status == LIVE:
                for g in gens:
                    if g["status"] == LIVE:
                        g["status"] = RETIRED
            gens.append(entry.to_dict())
            manifest["generations"] = gens
            self._write(manifest)
            return entry

    def _transition(
        self, instance_id: str, from_statuses: tuple[str, ...], to: str,
        stamp: str | None = None, retire_live: bool = False, note: str = "",
    ) -> Generation:
        with self._lock:
            manifest = self.read()
            target = None
            for g in manifest["generations"]:
                if g["instance_id"] == instance_id:
                    target = g
                    break
            if target is None:
                raise LifecycleError(
                    f"generation {instance_id} not in manifest {self.engine_key}"
                )
            if from_statuses and target["status"] not in from_statuses:
                raise LifecycleError(
                    f"generation {instance_id} is {target['status']!r}; "
                    f"expected one of {from_statuses} to move to {to!r}"
                )
            if retire_live:
                for g in manifest["generations"]:
                    if g["status"] == LIVE and g["instance_id"] != instance_id:
                        g["status"] = RETIRED
            target["status"] = to
            if stamp:
                target[stamp] = _now()
            if note:
                target["note"] = note
            # ONE write is the commit point: a SIGKILL before this line
            # leaves the old manifest; after it, the new one — whole either
            # way
            self._write(manifest)
            return Generation.from_dict(target)

    def start_canary(self, instance_id: str) -> Generation:
        return self._transition(instance_id, (STAGED,), CANARY)

    def promote(self, instance_id: str, note: str = "") -> Generation:
        """Flip a canary (or staged, for direct /reload swaps) generation
        to live; the previous live retires in the same atomic write.
        Promoting the CURRENT live is a no-op (idempotent /reload), and a
        retired/rolled-back generation may be re-promoted — the operator's
        explicit flip-back path."""
        current = self.get(instance_id)
        if current is not None and current.status == LIVE:
            return current
        return self._transition(
            instance_id, (CANARY, STAGED, RETIRED, ROLLED_BACK), LIVE,
            stamp="promoted_at", retire_live=True, note=note,
        )

    def rollback(self, instance_id: str, note: str = "") -> Generation:
        """Abort a canary: the generation is marked rolled_back and the
        live one keeps serving untouched."""
        return self._transition(
            instance_id, (CANARY, STAGED), ROLLED_BACK,
            stamp="rolled_back_at", note=note,
        )

    def mark_corrupt(self, instance_id: str, reason: str = "") -> None:
        """Demote a generation whose bytes failed verification so the
        fallback walk never retries it.  Tolerates a missing entry (the
        manifest may predate the blob)."""
        try:
            self._transition(
                instance_id, (), ROLLED_BACK, stamp="rolled_back_at",
                note=f"corrupt: {reason}" if reason else "corrupt",
            )
        except LifecycleError:
            log.warning(
                "could not mark corrupt generation in manifest",
                extra={"instance": instance_id, "engine": self.engine_key},
            )

    # -- verification --------------------------------------------------------

    def verify(self, gen: Generation | str) -> None:
        """Recompute the stored-bytes checksum and compare; raises
        :class:`CorruptModelError` on mismatch or missing bytes.

        Generations recorded with per-part checksums verify part-by-part,
        so ONE corrupt factor shard is reported BY NAME (and still trips
        the same last-good fallback walk at bind time)."""
        if isinstance(gen, str):
            found = self.get(gen)
            if found is None:
                raise LifecycleError(
                    f"generation {gen} not in manifest {self.engine_key}"
                )
            gen = found
        if gen.part_checksums:
            actual_parts = compute_part_checksums(
                self.models_store, gen.instance_id
            )
            if actual_parts is not None:
                bad = sorted(
                    set(gen.part_checksums.items())
                    ^ set(actual_parts.items())
                )
                bad_names = sorted({name for name, _ in bad})
                if bad_names:
                    raise CorruptModelError(
                        f"model shards {bad_names} of generation "
                        f"{gen.instance_id} do not match their manifest "
                        "checksums — refusing to serve a corrupt model"
                    )
                return
            # layout changed under the manifest (sharded -> single blob):
            # fall through to the whole-bytes comparison below
        actual = compute_checksum(self.models_store, gen.instance_id)
        if actual != gen.checksum:
            raise CorruptModelError(
                f"model bytes for generation {gen.instance_id} do not match "
                f"the manifest checksum (stored {gen.checksum[:12]}…, "
                f"recomputed {actual[:12]}…) — refusing to serve a corrupt "
                "model"
            )

    def rollback_stats(self) -> dict[str, Any]:
        """Recent-rollback summary for status surfaces."""
        gens = self.generations()
        last_rb = max(
            (g.rolled_back_at or 0.0 for g in gens if g.status == ROLLED_BACK),
            default=None,
        )
        return {
            "rolled_back": sum(1 for g in gens if g.status == ROLLED_BACK),
            "last_rollback_at": last_rb,
        }

    def snapshot(self) -> dict[str, Any]:
        """The /lifecycle.json manifest half."""
        manifest = self.read()
        live = canary = live_plan = None
        for g in manifest["generations"]:
            if g["status"] == LIVE:
                live = g["instance_id"]
                live_plan = g.get("shard_plan")
            elif g["status"] == CANARY:
                canary = g["instance_id"]
        return {
            "engine": self.engine_key,
            "schema": manifest.get("schema", SCHEMA_VERSION),
            "live": live,
            "canary": canary,
            # the live generation's serving layout (mesh axes + per-array
            # specs) — what `pio status`/the dashboard show as "mesh shape"
            "shard_plan": live_plan,
            "generations": manifest["generations"],
            **self.rollback_stats(),
        }
