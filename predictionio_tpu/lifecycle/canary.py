"""Canary rollout: deterministic traffic split, guardrails, and the
promote-or-rollback decision.

A staged generation serves a fixed *entity-hash fraction* of traffic: the
query's joinable entity id (the same field the quality joiner keys on)
hashes through :func:`~predictionio_tpu.data.storage.base.entity_shard`,
so one user consistently lands on one side of the split — their feedback
events join back to the variant that actually served them, and repeated
flips cannot bounce a user between models mid-session.  Queries with no
entity id always serve live (the safe default: they cannot be joined, so
they cannot inform the decision either).

Guardrails (checked by :meth:`CanaryDecider.evaluate`):

- **auto-abort** — once the canary has ``min_requests`` answers, an error
  rate above ``max_error_rate`` or a p95 latency beyond
  ``latency_ratio`` x the live p95 rolls it back immediately;
- **promotion** — only after ``min_joined`` feedback events joined to the
  canary variant show its online metric within ``max_metric_regression``
  of live does the canary promote; a canary that cannot gather evidence
  inside ``max_canary_s`` rolls back (fail-safe: the burden of proof is on
  the NEW model).

Everything is clock-injected so the chaos suite runs frozen-time.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any

from predictionio_tpu.data.storage.base import entity_shard

#: hash-space granularity of the traffic split (0.01% steps)
_SPLIT_BUCKETS = 10_000

#: variant label canary predictions are logged under in the QualityMonitor
CANARY_VARIANT = "canary"


def in_canary_fraction(entity: str | None, fraction: float) -> bool:
    """Deterministic split: the same entity id always lands on the same
    side for a given fraction.  No entity -> live."""
    if not entity or fraction <= 0.0:
        return False
    if fraction >= 1.0:
        return True
    bucket = entity_shard("pio_canary", str(entity), _SPLIT_BUCKETS)
    return bucket < int(fraction * _SPLIT_BUCKETS)


@dataclass(frozen=True)
class CanaryPolicy:
    """Rollout knobs (docs/robustness.md#model-lifecycle)."""

    #: entity-hash fraction of traffic the canary serves
    fraction: float = 0.1
    #: answers required before the error/latency guardrails judge
    min_requests: int = 50
    #: 5xx fraction that aborts the canary outright
    max_error_rate: float = 0.05
    #: canary p95 may be at most this multiple of the live p95
    latency_ratio: float = 3.0
    #: joined feedback samples required before promotion
    min_joined: int = 20
    #: online metric compared between variants
    metric: str = "hit_rate"
    #: allowed fractional drop of the canary metric vs live
    max_metric_regression: float = 0.10
    #: canary lifetime bound; undecided past this -> rollback (fail-safe)
    max_canary_s: float = 3600.0


class VariantStats:
    """Per-variant request counters + a bounded latency reservoir."""

    __slots__ = ("requests", "errors", "_lat", "_cap")

    def __init__(self, cap: int = 1024):
        self.requests = 0
        self.errors = 0
        self._lat: list[float] = []
        self._cap = cap

    def observe(self, status: int, seconds: float) -> None:
        self.requests += 1
        if status >= 500:
            self.errors += 1
        if len(self._lat) >= self._cap:
            # overwrite round-robin: O(1), keeps a rolling window
            self._lat[self.requests % self._cap] = seconds
        else:
            self._lat.append(seconds)

    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    def p95(self) -> float | None:
        if not self._lat:
            return None
        ordered = sorted(self._lat)
        return ordered[min(int(0.95 * len(ordered)), len(ordered) - 1)]

    def to_dict(self) -> dict[str, Any]:
        p95 = self.p95()
        return {
            "requests": self.requests,
            "errors": self.errors,
            "error_rate": round(self.error_rate(), 6),
            "p95_s": round(p95, 6) if p95 is not None else None,
        }


class CanaryTracker:
    """Live + canary request stats for ONE rollout attempt.

    The serving handlers call :meth:`observe` per answer (a few counter
    bumps under one lock); the controller reads the aggregate.  ``reset``
    starts a fresh attempt so a new canary never inherits the error budget
    of the previous one.
    """

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._live = VariantStats()
        self._canary = VariantStats()
        self.started_at: float | None = None

    def start(self) -> None:
        with self._lock:
            self._live = VariantStats()
            self._canary = VariantStats()
            self.started_at = self._clock()

    def stop(self) -> None:
        with self._lock:
            self.started_at = None

    def observe(self, is_canary: bool, status: int, seconds: float) -> None:
        with self._lock:
            (self._canary if is_canary else self._live).observe(
                status, seconds
            )

    def age_s(self) -> float | None:
        with self._lock:
            if self.started_at is None:
                return None
            return self._clock() - self.started_at

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "started_at": self.started_at,
                "live": self._live.to_dict(),
                "canary": self._canary.to_dict(),
            }


#: evaluate() verdicts
CONTINUE, PROMOTE, ROLLBACK = "continue", "promote", "rollback"


class CanaryDecider:
    """The promote-or-rollback judgment, pure function of the stats."""

    def __init__(self, policy: CanaryPolicy):
        self.policy = policy

    def evaluate(
        self,
        tracker_snapshot: dict[str, Any],
        quality_comparison: dict[str, Any] | None,
        age_s: float | None,
    ) -> tuple[str, str]:
        """Returns ``(verdict, reason)``.

        ``quality_comparison`` is
        :meth:`QualityMonitor.compare_variants` output (live/canary metric
        values + the canary joined count), or None when no monitor feeds
        the rollout.
        """
        p = self.policy
        canary = tracker_snapshot["canary"]
        live = tracker_snapshot["live"]
        # guardrail 1: error-rate burn, judged as soon as the sample is big
        # enough to mean something
        if canary["requests"] >= p.min_requests:
            if canary["error_rate"] > p.max_error_rate:
                return ROLLBACK, (
                    f"canary error rate {canary['error_rate']:.3f} exceeds "
                    f"guardrail {p.max_error_rate:.3f} over "
                    f"{canary['requests']} requests"
                )
            # guardrail 2: latency SLO burn relative to live
            if (
                canary["p95_s"] is not None
                and live["p95_s"] is not None
                and live["p95_s"] > 0
                and canary["p95_s"] > live["p95_s"] * p.latency_ratio
            ):
                return ROLLBACK, (
                    f"canary p95 {canary['p95_s']:.4f}s exceeds "
                    f"{p.latency_ratio:g}x live p95 {live['p95_s']:.4f}s"
                )
        # promotion: enough joined evidence and no online-metric regression
        if canary["requests"] >= p.min_requests:
            joined = (quality_comparison or {}).get("canary_joined", 0)
            if p.min_joined <= 0 or joined >= p.min_joined:
                regressed, why = self._metric_regressed(quality_comparison)
                if regressed:
                    return ROLLBACK, why
                return PROMOTE, (
                    f"no regression after {canary['requests']} requests"
                    + (f", {joined} joined samples" if joined else "")
                )
        # fail-safe: a canary that cannot prove itself does not linger
        if age_s is not None and age_s > p.max_canary_s:
            return ROLLBACK, (
                f"canary undecided after {age_s:.0f}s "
                f"(max {p.max_canary_s:.0f}s) — burden of proof not met"
            )
        return CONTINUE, "gathering evidence"

    def _metric_regressed(
        self, comparison: dict[str, Any] | None
    ) -> tuple[bool, str]:
        p = self.policy
        if not comparison:
            return False, ""
        live_v = comparison.get("live_value")
        canary_v = comparison.get("canary_value")
        if live_v is None or canary_v is None or live_v <= 0:
            return False, ""  # nothing comparable yet
        floor = live_v * (1.0 - p.max_metric_regression)
        if canary_v < floor:
            return True, (
                f"online {p.metric} regressed: canary {canary_v:.4f} < "
                f"{floor:.4f} ({p.max_metric_regression:.0%} under live "
                f"{live_v:.4f})"
            )
        return False, ""
