"""The autoscaler: the controller that closes the capacity loop.

``/capacity.json`` (obs/capacity.py) computes ``recommended_replicas`` per
replica; :func:`~predictionio_tpu.fleet.membership.fleet_capacity`
aggregates the scrapes fleet-wide; this module is what finally *obeys*
the signal — the LifecycleController idiom: a daemon thread around a
test-drivable :meth:`Autoscaler.tick`.

Each tick:

1. refresh membership + scrape every replica's ``/capacity.json``;
2. aggregate into a desired size (an operator pin via
   ``pio fleet scale`` / ``POST /fleet/scale`` overrides the model);
3. apply **hysteresis** (``scale_up_patience`` / ``scale_down_patience``
   consecutive ticks must agree before anything moves — one noisy scrape
   must not flap the fleet) and **cooldown** (no two scaling actions
   within ``cooldown_s`` — a replica that just booted hasn't absorbed
   load yet, scaling again on the same signal would overshoot);
4. scale **up** by spawning one replica through the
   :class:`ReplicaSpawner` (the `pio deploy` daemon machinery), or
   **down** by draining one: quiesce in the
   :class:`~predictionio_tpu.fleet.membership.FleetState` (routing stops
   immediately), wait for the replica's generation-refcount drain (its
   ``/status.json`` reports per-generation in-flight counts and
   micro-batch queue state), then SIGTERM via the pidfile
   (:func:`~predictionio_tpu.tools.daemon.stop_pidfile`).  One action per
   tick: convergence is deliberate, divergence is bounded.

Scaling the CPU tier (router + replicas on cheap hosts) independently of
the accelerator tier is the cost-performance framing of arxiv 2509.14920.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Any, Callable, Mapping

from predictionio_tpu.fleet.membership import FleetState, fleet_capacity
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("predictionio_tpu.fleet")


@dataclass(frozen=True)
class AutoscalerPolicy:
    """Sizing bounds + hysteresis/cooldown knobs (docs/fleet.md)."""

    min_replicas: int = 1
    max_replicas: int = 4
    #: consecutive ticks that must recommend growing before one spawn
    scale_up_patience: int = 2
    #: consecutive ticks that must recommend shrinking before one drain —
    #: deliberately laxer than up: under-capacity burns the SLO, over-
    #: capacity burns money
    scale_down_patience: int = 3
    #: minimum seconds between scaling actions
    cooldown_s: float = 30.0
    #: controller loop period (the daemon-thread pacing)
    tick_interval_s: float = 5.0
    #: how long a drain may wait on a replica's in-flight work before the
    #: SIGTERM escalation path handles it anyway
    drain_timeout_s: float = 30.0

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "AutoscalerPolicy":
        import os

        e = env or os.environ
        return cls(
            min_replicas=int(e.get("PIO_FLEET_MIN_REPLICAS", 1)),
            max_replicas=int(e.get("PIO_FLEET_MAX_REPLICAS", 4)),
            scale_up_patience=int(e.get("PIO_FLEET_UP_PATIENCE", 2)),
            scale_down_patience=int(e.get("PIO_FLEET_DOWN_PATIENCE", 3)),
            cooldown_s=float(e.get("PIO_FLEET_COOLDOWN_S", 30.0)),
            tick_interval_s=float(e.get("PIO_FLEET_TICK_S", 5.0)),
            drain_timeout_s=float(e.get("PIO_FLEET_DRAIN_TIMEOUT_S", 30.0)),
        )


class ReplicaSpawner:
    """What the autoscaler scales through.  Implementations own the
    replica *processes*; the FleetState owns the *membership*."""

    def spawn(self) -> str:
        """Start one replica; returns its base URL once it answers
        /readyz (or at least binds its port)."""
        raise NotImplementedError

    def drain(self, url: str) -> None:
        """Wait for the (already-quiesced) replica's in-flight work to
        finish, then stop the process."""
        raise NotImplementedError

    def stop_all(self) -> None:
        """Tear down every replica this spawner owns (fleet shutdown)."""


class LocalProcessSpawner(ReplicaSpawner):
    """Replicas as local ``pio deploy`` daemon subprocesses — the
    single-host proof of the loop (a k8s/Ray spawner implements the same
    two methods against its scheduler).

    Each spawn allocates a port, detaches ``python -m
    predictionio_tpu.tools.cli deploy <deploy_args> --ip <host> --port N``
    with a ``$PIO_HOME/pids/replica-<port>.pid`` pidfile, and waits for
    ``/readyz`` to answer 200.  Drain polls the replica's ``/status.json``
    until no generation holds an in-flight request and the micro-batch
    queue is idle, then SIGTERMs (escalating to SIGKILL) via
    :func:`~predictionio_tpu.tools.daemon.stop_pidfile`.
    """

    def __init__(
        self,
        deploy_args: list[str],
        host: str = "127.0.0.1",
        base_port: int | None = None,
        ready_timeout_s: float = 180.0,
        drain_timeout_s: float = 30.0,
        poll_interval_s: float = 0.2,
    ):
        self.deploy_args = list(deploy_args)
        self.host = host
        self._next_port = base_port
        self.ready_timeout_s = ready_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._pidfiles: dict[str, Any] = {}  # url -> Path
        self._pacer = threading.Event()

    def _alloc_port(self) -> int:
        import socket

        with self._lock:
            if self._next_port is not None:
                port = self._next_port
                self._next_port += 1
                return port
        with socket.socket() as s:
            s.bind((self.host, 0))
            return s.getsockname()[1]

    def _get_json(self, url: str, timeout: float = 2.0) -> tuple[int, Any]:
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return r.status, json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read().decode("utf-8"))
            except ValueError:
                return e.code, None

    def spawn(self) -> str:
        from predictionio_tpu.tools import daemon

        port = self._alloc_port()
        url = f"http://{self.host}:{port}"
        pidfile = daemon._pid_dir() / f"replica-{port}.pid"
        daemon.spawn_daemon(
            ["deploy", *self.deploy_args, "--ip", self.host, "--port", str(port)],
            pidfile,
        )
        with self._lock:
            self._pidfiles[url] = pidfile
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            try:
                status, _ = self._get_json(url + "/readyz")
                if status == 200:
                    log.info("replica spawned and ready at %s", url)
                    return url
            except Exception:
                if not daemon.pid_alive(daemon.read_pidfile(pidfile)):
                    raise RuntimeError(
                        f"replica subprocess for {url} died at boot; see "
                        f"its log next to {pidfile}"
                    )
            self._pacer.wait(self.poll_interval_s)
        raise TimeoutError(f"replica {url} never answered /readyz")

    def pid_of(self, url: str) -> int | None:
        """The live pid behind a spawned replica url (None when unknown or
        dead) — chaos harnesses SIGKILL through this instead of groping
        pidfiles."""
        from predictionio_tpu.tools import daemon

        with self._lock:
            pidfile = self._pidfiles.get(url)
        if pidfile is None:
            return None
        pid = daemon.read_pidfile(pidfile)
        return pid if daemon.pid_alive(pid) else None

    def wait_replica_drained(self, url: str, timeout_s: float | None = None) -> bool:
        """Poll the replica's /status.json generation-refcount surface
        until idle; True when it drained inside the timeout."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.drain_timeout_s
        )
        while time.monotonic() < deadline:
            try:
                status, body = self._get_json(url + "/status.json")
            except Exception:
                return True  # already gone: nothing left to drain
            if status == 200 and isinstance(body, dict):
                if not body.get("inflightGenerations") and not body.get(
                    "batcherBusy"
                ):
                    return True
            self._pacer.wait(self.poll_interval_s)
        return False

    def drain(self, url: str) -> None:
        from predictionio_tpu.tools import daemon

        drained = self.wait_replica_drained(url)
        if not drained:
            log.warning(
                "replica %s did not drain within %.0fs; stopping anyway",
                url, self.drain_timeout_s,
            )
        with self._lock:
            pidfile = self._pidfiles.pop(url, None)
        if pidfile is not None:
            won = daemon.stop_pidfile(pidfile)
            log.info("replica %s stopped (%s)", url, won or "not running")

    def stop_all(self) -> None:
        from predictionio_tpu.tools import daemon

        with self._lock:
            pidfiles = dict(self._pidfiles)
            self._pidfiles.clear()
        for url, pidfile in pidfiles.items():
            won = daemon.stop_pidfile(pidfile)
            log.info("replica %s stopped (%s)", url, won or "not running")


class Autoscaler:
    """Scrape → aggregate → hysteresis → spawn/drain, one action per tick."""

    def __init__(
        self,
        fleet: FleetState,
        spawner: ReplicaSpawner,
        policy: AutoscalerPolicy | None = None,
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
        alerts: Any | None = None,
    ):
        self.fleet = fleet
        self.spawner = spawner
        self.policy = policy or AutoscalerPolicy()
        self._clock = clock
        #: an AlertEvaluator to narrate into: every scale action is
        #: recorded as a synthetic resolved-alert event, so an incident
        #: timeline read hours later explains WHY capacity changed
        self.alerts = alerts
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopping = False
        self._target_override: int | None = None
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: float | None = None
        self._last_event: dict[str, Any] | None = None
        reg = registry or REGISTRY
        self._m_desired = reg.gauge(
            "pio_autoscaler_desired_replicas",
            "Fleet size the autoscaler is converging toward",
        )
        self._m_actions = reg.counter(
            "pio_autoscaler_actions_total",
            "Scaling actions taken, by direction",
            labelnames=("action",),
        )

    # -- operator override ---------------------------------------------------

    def set_target(self, n: int | None) -> None:
        """Pin the fleet size (None returns to capacity-model control).
        A pin still honors the min/max bounds and the drain protocol, but
        skips hysteresis — the operator already decided."""
        with self._lock:
            self._target_override = n
            self._up_streak = 0
            self._down_streak = 0

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": True,
                "target_override": self._target_override,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "last_event": dict(self._last_event) if self._last_event else None,
                "policy": {
                    "min_replicas": self.policy.min_replicas,
                    "max_replicas": self.policy.max_replicas,
                    "scale_up_patience": self.policy.scale_up_patience,
                    "scale_down_patience": self.policy.scale_down_patience,
                    "cooldown_s": self.policy.cooldown_s,
                },
            }

    def _note(self, kind: str, **detail: Any) -> None:
        event = {"event": kind, "at": self._clock(), **detail}
        with self._lock:
            self._last_event = event
        log.info("autoscaler %s", kind, extra=detail)
        if self.alerts is not None:
            try:
                self.alerts.note_event(
                    f"autoscaler_{kind}",
                    f"autoscaler {kind}: "
                    + " ".join(f"{k}={v}" for k, v in sorted(detail.items())),
                    severity=(
                        "warning" if kind == "spawn_failed" else "info"
                    ),
                    key=str(detail.get("replica") or ""),
                    **detail,
                )
            except Exception:
                log.exception("autoscaler alert-event note failed")

    # -- the loop ------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="pio-autoscaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=10.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                self.tick()
            except Exception:
                log.exception("autoscaler tick failed")
            self._wake.wait(self.policy.tick_interval_s)
            self._wake.clear()

    # -- one controller step -------------------------------------------------

    def desired_size(self, capacity: Mapping[str, Any]) -> int | None:
        """The size this tick wants: operator pin, else the fleet capacity
        model's recommendation (None when the model has no signal yet),
        clamped to [min_replicas, max_replicas]."""
        with self._lock:
            pinned = self._target_override
        raw = pinned if pinned is not None else capacity.get("recommended_replicas")
        if raw is None:
            if capacity.get("scale_hint") == "up":
                # burn-only signal (no computable ceiling): grow by one
                raw = self.fleet.active_count() + 1
            else:
                return None
        return max(self.policy.min_replicas, min(int(raw), self.policy.max_replicas))

    def tick(self) -> str | None:
        """One step; returns "scale_up" | "scale_down" | None (held)."""
        self.fleet.refresh()
        capacity = fleet_capacity(self.fleet)
        current = self.fleet.active_count()
        desired = self.desired_size(capacity)
        if desired is not None:
            self._m_desired.set(desired)
        with self._lock:
            pinned = self._target_override is not None
            if desired is None or desired == current:
                self._up_streak = 0
                self._down_streak = 0
                return None
            if desired > current:
                self._up_streak += 1
                self._down_streak = 0
                ready = pinned or self._up_streak >= self.policy.scale_up_patience
            else:
                self._down_streak += 1
                self._up_streak = 0
                ready = pinned or self._down_streak >= self.policy.scale_down_patience
            in_cooldown = (
                self._last_action_at is not None
                and self._clock() - self._last_action_at < self.policy.cooldown_s
            )
        if not ready or (in_cooldown and not pinned):
            return None
        if desired > current:
            return self._scale_up(current, desired)
        return self._scale_down(current, desired)

    def _scale_up(self, current: int, desired: int) -> str | None:
        try:
            url = self.spawner.spawn()
        except Exception as e:
            self._note("spawn_failed", error=str(e))
            log.error("replica spawn failed: %s", e)
            return None
        self.fleet.add(url)
        with self._lock:
            self._last_action_at = self._clock()
            self._up_streak = 0
        self._m_actions.labels("scale_up").inc()
        self._note("scale_up", replica=url, size=current + 1, desired=desired)
        return "scale_up"

    def _pick_victim(self) -> str | None:
        """Shrink from the tail of the membership list: the most recently
        added replica carries the fewest affine entities' history."""
        reps = [r for r in self.fleet.replicas() if not r.draining]
        return reps[-1].url if reps else None

    def _scale_down(self, current: int, desired: int) -> str | None:
        victim = self._pick_victim()
        if victim is None:
            return None
        # 1. stop routing (rendezvous hashing re-homes the victim's
        #    entities onto the survivors deterministically)
        self.fleet.quiesce(victim)
        # 2. wait on the replica's generation-refcount drain, then stop it
        try:
            self.spawner.drain(victim)
        except Exception as e:
            log.error("replica drain failed for %s: %s", victim, e)
        # 3. drop it from membership
        self.fleet.remove(victim)
        with self._lock:
            self._last_action_at = self._clock()
            self._down_streak = 0
        self._m_actions.labels("scale_down").inc()
        self._note("scale_down", replica=victim, size=current - 1, desired=desired)
        return "scale_down"
