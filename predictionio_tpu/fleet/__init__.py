"""Horizontal fleet layer: router + replica membership + autoscaler.

The capacity model (obs/capacity.py) emits ``recommended_replicas`` and
nothing consumed it; this package is the consumer.  Three pieces:

- :mod:`~predictionio_tpu.fleet.membership` — the :class:`FleetState`
  replica registry: health probing off each replica's ``/readyz``,
  per-replica circuit breakers, ``/capacity.json`` scrapes, and the
  consistent-hash (rendezvous over the HBEventsUtil md5 hash) entity
  affinity the router routes by;
- :mod:`~predictionio_tpu.fleet.router` — a thin CPU-tier HTTP front end
  proxying ``/queries.json`` to N prediction-server replicas with
  deadline-bounded retry-on-another-replica, serving ``/fleet.json`` and
  the fleet-aggregated ``/capacity.json``;
- :mod:`~predictionio_tpu.fleet.autoscaler` — the controller loop that
  closes the capacity loop: scrape → aggregate → hysteresis/cooldown →
  spawn or drain replica processes through the ``pio deploy`` machinery;
- :mod:`~predictionio_tpu.fleet.federation` — fleet-wide telemetry
  fan-in: the router's federated ``/metrics`` (every replica's families
  merged with a ``replica`` label) and fleet ``/alerts.json``, so one
  scrape watches the whole fleet.

See docs/fleet.md.
"""

from predictionio_tpu.fleet.autoscaler import (
    Autoscaler,
    AutoscalerPolicy,
    LocalProcessSpawner,
)
from predictionio_tpu.fleet.federation import (
    federated_alerts,
    federated_metrics_text,
    scrape_replicas,
)
from predictionio_tpu.fleet.membership import (
    FleetState,
    Replica,
    fleet_capacity,
)
from predictionio_tpu.fleet.router import create_router_app

__all__ = [
    "Autoscaler",
    "AutoscalerPolicy",
    "FleetState",
    "LocalProcessSpawner",
    "Replica",
    "create_router_app",
    "federated_alerts",
    "federated_metrics_text",
    "fleet_capacity",
    "scrape_replicas",
]
