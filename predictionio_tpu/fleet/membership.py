"""Fleet membership: who the replicas are, which of them are routable, and
what each one last said about its own capacity.

:class:`FleetState` is the one registry the router and the autoscaler both
read.  Per replica it tracks:

- **health** — a ``/readyz`` prober (daemon thread, test-driven
  :meth:`FleetState.probe_once`) ejects a replica after
  ``eject_after`` consecutive failed probes and re-admits it on the first
  healthy one, so a crashed replica stops receiving traffic within one
  probe interval and a revived one rejoins without operator action;
- **breaker state** — each replica gets a process-global
  :class:`~predictionio_tpu.resilience.breaker.CircuitBreaker`
  (``replica:<url>``), tripped by the router's forwarding failures:
  ejection-by-breaker reacts in milliseconds, the prober in seconds;
- **in-flight count** — router-side concurrent forwards, for /fleet.json
  and the dashboard panel;
- **capacity** — the last ``/capacity.json`` scrape, the autoscaler's
  input (:func:`fleet_capacity` aggregates them fleet-wide).

Replica affinity is rendezvous (highest-random-weight) hashing over the
same md5 hash family as
:func:`~predictionio_tpu.data.storage.base.entity_shard` — the
HBEventsUtil row-key hash the PR 7 canary split and the event-store scan
sharding already key on.  One entity consistently lands on one replica
(keeping any per-user device caches warm), membership changes only move
the keys of the replicas that changed, and because the canary split hashes
the same entity id *inside* each replica, canary assignment is coherent
fleet-wide no matter which replica answers.

The membership source is a static URL list, refreshable from a file
(``PIO_FLEET_FILE``: JSON list or one URL per line — re-read when its
mtime changes) or the ``PIO_FLEET_REPLICAS`` comma list.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Iterable, Mapping

from predictionio_tpu.data.storage.base import entity_shard
from predictionio_tpu.obs.capacity import TARGET_UTILIZATION
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.resilience.breaker import CircuitBreaker, get_breaker

log = logging.getLogger("predictionio_tpu.fleet")

#: rendezvous-hash score space (any large modulus works; this one keeps the
#: md5-derived scores comfortably away from collisions at fleet sizes)
_HASH_SPACE = 1 << 31

#: response header naming the replica that answered a routed request
REPLICA_HEADER = "X-Pio-Replica"


def replica_id_of(url: str) -> str:
    """A compact stable id for a replica URL (host:port)."""
    trimmed = url.split("://", 1)[-1].rstrip("/")
    return trimmed


class Replica:
    """One replica's registry record.  All fields are guarded by the owning
    :class:`FleetState`'s lock; reads for display go through
    :meth:`FleetState.snapshot`."""

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url.rstrip("/")
        self.replica_id = replica_id_of(url)
        self.breaker = breaker
        #: /readyz verdict; a fresh replica starts routable so a static
        #: fleet works before the first probe completes
        self.healthy = True
        #: quiesced by the autoscaler: routing stops, in-flight work drains
        self.draining = False
        self.consecutive_probe_failures = 0
        self.ejections_total = 0
        self.inflight = 0
        self.last_probe_at: float | None = None
        self.last_probe_error: str | None = None
        self.last_capacity: dict | None = None
        self.last_capacity_at: float | None = None

    def routable(self) -> bool:
        return self.healthy and not self.draining and self.breaker.state != "open"


class FleetState:
    """The replica registry: membership + health + capacity, one lock.

    ``start()`` runs the /readyz prober on a daemon thread; tests drive
    :meth:`probe_once` / :meth:`scrape_capacity_once` directly (the
    LifecycleController idiom).
    """

    def __init__(
        self,
        replicas: Iterable[str] = (),
        name: str = "fleet",
        registry: MetricsRegistry | None = None,
        probe_interval_s: float = 2.0,
        probe_timeout_s: float = 2.0,
        eject_after: int = 2,
        source_file: str | None = None,
        access_key: str | None = None,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 2.0,
    ):
        self.name = name
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.eject_after = max(int(eject_after), 1)
        self.source_file = source_file
        self.access_key = access_key
        self._breaker_threshold = breaker_threshold
        self._breaker_reset_s = breaker_reset_s
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._rr = 0  # round-robin cursor for entity-less queries
        self._last_capacity_scrape_at: float | None = None
        self._source_mtime: float | None = None
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopping = False
        reg = registry or REGISTRY
        self._m_replicas = reg.gauge(
            "pio_fleet_replicas",
            "Fleet replica counts by state",
            labelnames=("state",),
        )
        self._m_ejections = reg.counter(
            "pio_fleet_ejections_total",
            "Replicas ejected from routing by the /readyz prober",
            labelnames=("replica",),
        )
        for url in replicas:
            self._add_locked_free(url)
        self._update_gauges()

    @classmethod
    def from_env(
        cls, env: Mapping[str, str] | None = None, **kwargs: Any
    ) -> "FleetState":
        """Build from ``PIO_FLEET_REPLICAS`` (comma-separated URLs) and/or
        ``PIO_FLEET_FILE`` (JSON list or one-URL-per-line; re-read on
        mtime change by :meth:`refresh`)."""
        e = env or os.environ
        urls = [
            u.strip()
            for u in e.get("PIO_FLEET_REPLICAS", "").split(",")
            if u.strip()
        ]
        kwargs.setdefault("source_file", e.get("PIO_FLEET_FILE") or None)
        fleet = cls(urls, **kwargs)
        fleet.refresh()
        return fleet

    # -- membership ----------------------------------------------------------

    def _add_locked_free(self, url: str) -> Replica:
        """Create the record WITHOUT holding the lock (get_breaker locks
        internally); callers insert under the lock."""
        url = url.rstrip("/")
        breaker = get_breaker(
            f"replica:{replica_id_of(url)}",
            failure_threshold=self._breaker_threshold,
            reset_timeout_s=self._breaker_reset_s,
        )
        rep = Replica(url, breaker)
        with self._lock:
            existing = self._replicas.get(url)
            if existing is not None:
                return existing
            self._replicas[url] = rep
        return rep

    def add(self, url: str) -> Replica:
        rep = self._add_locked_free(url)
        self._update_gauges()
        return rep

    def remove(self, url: str) -> None:
        with self._lock:
            self._replicas.pop(url.rstrip("/"), None)
        self._update_gauges()

    def set_replicas(self, urls: Iterable[str]) -> None:
        """Reconcile membership to exactly ``urls`` (file/env refresh):
        new URLs join, missing ones leave, existing records keep their
        health/breaker history."""
        want = {u.rstrip("/") for u in urls if u.strip()}
        with self._lock:
            have = set(self._replicas)
        for url in want - have:
            self._add_locked_free(url)
        with self._lock:
            for url in have - want:
                self._replicas.pop(url, None)
        self._update_gauges()

    def refresh(self) -> bool:
        """Re-read the source file when its mtime changed; True when
        membership was reconciled.  A file we cannot read or parse keeps
        the CURRENT membership (and keeps retrying: the mtime is only
        recorded after a successful apply) — a half-written or malformed
        file must never be applied as a full fleet drain."""
        path = self.source_file
        if not path:
            return False
        try:
            mtime = os.stat(path).st_mtime
        except OSError:
            return False
        with self._lock:
            if self._source_mtime == mtime:
                return False
        try:
            text = open(path, encoding="utf-8").read()
        except OSError as e:
            log.warning("fleet source file %s unreadable: %s", path, e)
            return False
        try:
            parsed = json.loads(text)
            if not isinstance(parsed, list) or not all(
                isinstance(u, str) for u in parsed
            ):
                log.warning(
                    "fleet source file %s is JSON but not a list of URL "
                    "strings; keeping current membership", path,
                )
                return False
            urls = parsed
        except ValueError:
            urls = [ln.strip() for ln in text.splitlines() if ln.strip()]
        self.set_replicas(urls)
        with self._lock:
            self._source_mtime = mtime
        log.info("fleet membership refreshed from %s: %d replicas", path, len(urls))
        return True

    # -- reads ---------------------------------------------------------------

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def get(self, url: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(url.rstrip("/"))

    def routable(self) -> list[Replica]:
        with self._lock:
            return [r for r in self._replicas.values() if r.routable()]

    def active_count(self) -> int:
        """Replicas the autoscaler counts as 'current size': everything
        not already draining (an unhealthy replica is still fleet capacity
        being paid for — the autoscaler must not double-spawn over a blip)."""
        with self._lock:
            return sum(1 for r in self._replicas.values() if not r.draining)

    def route_order(self, entity: str | None) -> list[Replica]:
        """Routing order for one query: rendezvous hashing over the
        ``entity_shard`` md5 family — descending score, so the head is the
        entity's home replica and the tail is the deterministic failover
        order (retry-elsewhere walks it).  Entity-less queries rotate
        round-robin (nothing to be affine to)."""
        reps = self.routable()
        if len(reps) <= 1:
            return reps
        if entity:
            return sorted(
                reps,
                key=lambda r: entity_shard(
                    f"pio_fleet:{r.replica_id}", str(entity), _HASH_SPACE
                ),
                reverse=True,
            )
        with self._lock:
            self._rr += 1
            i = self._rr % len(reps)
        return reps[i:] + reps[:i]

    # -- router-side accounting ----------------------------------------------

    def note_inflight(self, replica: Replica, delta: int) -> None:
        with self._lock:
            replica.inflight = max(replica.inflight + delta, 0)

    def quiesce(self, url: str) -> Replica | None:
        """Stop routing to a replica (the first half of a drain); returns
        the record so the caller can wait on its in-flight work."""
        with self._lock:
            rep = self._replicas.get(url.rstrip("/"))
            if rep is not None:
                rep.draining = True
        self._update_gauges()
        return rep

    # -- probing -------------------------------------------------------------

    def _fetch_json(self, url: str, timeout: float) -> tuple[int, Any]:
        headers = {}
        if self.access_key:
            headers["Authorization"] = f"Bearer {self.access_key}"
        req = urllib.request.Request(url, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read().decode("utf-8"))
            except ValueError:
                return e.code, None

    def probe_once(self) -> dict[str, bool]:
        """One /readyz pass over the whole fleet; returns {url: healthy}.
        Ejection needs ``eject_after`` consecutive failures (one flaky
        probe must not flap routing); re-admission is immediate — a
        replica that answers ready IS ready."""
        out: dict[str, bool] = {}
        for rep in self.replicas():
            ok = False
            err: str | None = None
            try:
                status, _body = self._fetch_json(
                    rep.url + "/readyz", self.probe_timeout_s
                )
                ok = status == 200
                if not ok:
                    err = f"/readyz answered {status}"
            except Exception as e:  # unreachable / refused / timeout
                err = f"unreachable: {e}"
            now = time.monotonic()
            with self._lock:
                rep.last_probe_at = now
                rep.last_probe_error = err
                if ok:
                    if not rep.healthy:
                        log.info("replica %s re-admitted", rep.replica_id)
                    rep.consecutive_probe_failures = 0
                    rep.healthy = True
                    # a ready answer is positive proof of liveness: close
                    # the replica's breaker NOW instead of waiting out its
                    # reset window — "a replica that answers ready IS
                    # ready" must hold for routable(), not just healthy
                    rep.breaker.reset()
                else:
                    rep.consecutive_probe_failures += 1
                    if (
                        rep.healthy
                        and rep.consecutive_probe_failures >= self.eject_after
                    ):
                        rep.healthy = False
                        rep.ejections_total += 1
                        self._m_ejections.labels(rep.replica_id).inc()
                        log.warning(
                            "replica %s ejected (%s)", rep.replica_id, err
                        )
            out[rep.url] = ok
        self._update_gauges()
        return out

    def note_forward_success(self, replica: Replica) -> None:
        """The router got an HTTP answer from the replica: it is alive.
        Resets the failure streak so interleaved transient transport
        errors can never accumulate to an ejection."""
        with self._lock:
            replica.consecutive_probe_failures = 0
        self._update_gauges()

    def note_forward_failure(self, replica: Replica) -> None:
        """The router saw a transport failure: count it like a probe
        failure so a corpse is ejected by traffic even between probes (the
        breaker already stops routing in the meantime).  Ejection here
        requires the prober loop to be RUNNING — only a healthy probe
        re-admits, so without one (static/bench fleets) a couple of
        transient errors would eject a live replica forever; in that mode
        the breaker alone governs, and it recovers on its own through
        half-open trials."""
        with self._lock:
            replica.consecutive_probe_failures += 1
            prober_running = self._thread is not None
            if (
                prober_running
                and replica.healthy
                and replica.consecutive_probe_failures >= self.eject_after
            ):
                replica.healthy = False
                replica.ejections_total += 1
                self._m_ejections.labels(replica.replica_id).inc()
                log.warning(
                    "replica %s ejected (forward failures)", replica.replica_id
                )
        self._update_gauges()

    def scrape_capacity_once(self) -> dict[str, dict | None]:
        """One /capacity.json pass over the healthy replicas — the
        autoscaler's input.  A failed scrape clears nothing: the last
        snapshot stays (staleness is visible via last_capacity_at)."""
        out: dict[str, dict | None] = {}
        for rep in self.replicas():
            with self._lock:
                skip = not rep.healthy
            if skip:
                out[rep.url] = None
                continue
            body: dict | None = None
            try:
                status, payload = self._fetch_json(
                    rep.url + "/capacity.json", self.probe_timeout_s
                )
                if status == 200 and isinstance(payload, dict):
                    body = payload
            except Exception as e:
                log.debug("capacity scrape of %s failed: %s", rep.replica_id, e)
            if body is not None:
                with self._lock:
                    rep.last_capacity = body
                    rep.last_capacity_at = time.monotonic()
            out[rep.url] = body
        with self._lock:
            self._last_capacity_scrape_at = time.monotonic()
        return out

    def capacity_scrape_stale(self, max_age_s: float) -> bool:
        """True when no scrape pass finished within ``max_age_s`` — lets a
        serving-path reader (the router's /capacity.json) reuse the cached
        reports instead of re-fanning N HTTP calls per request while an
        autoscaler or watcher already scrapes on a cadence."""
        with self._lock:
            at = self._last_capacity_scrape_at
        return at is None or time.monotonic() - at > max_age_s

    # -- the probe loop ------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="pio-fleet-prober", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                self.refresh()
                self.probe_once()
            except Exception:
                log.exception("fleet probe pass failed")
            self._wake.wait(self.probe_interval_s)
            self._wake.clear()

    # -- exposition ----------------------------------------------------------

    def _update_gauges(self) -> None:
        with self._lock:
            reps = list(self._replicas.values())
            healthy = sum(1 for r in reps if r.healthy and not r.draining)
            ejected = sum(1 for r in reps if not r.healthy)
            draining = sum(1 for r in reps if r.draining)
        self._m_replicas.labels("healthy").set(healthy)
        self._m_replicas.labels("ejected").set(ejected)
        self._m_replicas.labels("draining").set(draining)

    def capacity_reports(self) -> list[tuple[Replica, dict | None]]:
        """(replica, last /capacity.json body) pairs, read under the lock —
        the :func:`fleet_capacity` input."""
        with self._lock:
            return [(r, r.last_capacity) for r in self._replicas.values()]

    def snapshot(self) -> dict[str, Any]:
        """The /fleet.json body."""
        rows = []
        with self._lock:
            for r in self._replicas.values():
                cap = r.last_capacity or {}
                rows.append({
                    "replica": r.replica_id,
                    "url": r.url,
                    "healthy": r.healthy,
                    "draining": r.draining,
                    "routable": r.routable(),
                    "breaker": r.breaker.state,
                    "inflight": r.inflight,
                    "consecutive_probe_failures": r.consecutive_probe_failures,
                    "ejections_total": r.ejections_total,
                    "last_probe_error": r.last_probe_error,
                    "capacity": {
                        "max_sustainable_qps": cap.get("max_sustainable_qps"),
                        "headroom_frac": cap.get("headroom_frac"),
                        "recommended_replicas": cap.get("recommended_replicas"),
                        "scale_hint": cap.get("scale_hint"),
                    }
                    if cap
                    else None,
                })
        return {
            "name": self.name,
            "replicas": rows,
            "total": len(rows),
            "healthy": sum(1 for r in rows if r["healthy"] and not r["draining"]),
            "routable": sum(1 for r in rows if r["routable"]),
            "source_file": self.source_file,
        }


def fleet_capacity(fleet: FleetState, scrape: bool = True) -> dict[str, Any]:
    """The fleet-aggregated ``/capacity.json`` body: sum of the replica
    ceilings, the worst (minimum) headroom, and a fleet-level recommended
    replica count — what ``pio capacity --url <router>`` reads and the
    autoscaler acts on.

    Fleet sizing: ``ceil(total observed QPS / (TARGET_UTILIZATION × mean
    per-replica ceiling))`` — the per-replica ``recommended_replicas``
    assumes that replica's OWN load continues, which under a balanced
    router is total/N, so summing or maxing them would mis-size the fleet.
    A replica whose SLO is burning adds one (the same escape hatch the
    single-replica model uses).
    """
    if scrape:
        fleet.scrape_capacity_once()
    per_replica: dict[str, dict | None] = {}
    ceilings: list[float] = []
    observed: list[float] = []
    headrooms: list[float] = []
    burning = False
    caveats: list[str] = []
    for rep, cap in fleet.capacity_reports():
        per_replica[rep.replica_id] = (
            {
                "max_sustainable_qps": cap.get("max_sustainable_qps"),
                "observed_qps": (cap.get("inputs") or {}).get("observed_qps"),
                "headroom_frac": cap.get("headroom_frac"),
                "recommended_replicas": cap.get("recommended_replicas"),
                "scale_hint": cap.get("scale_hint"),
            }
            if cap
            else None
        )
        if not cap:
            caveats.append(f"no capacity scrape from {rep.replica_id} yet")
            continue
        if isinstance(cap.get("max_sustainable_qps"), (int, float)):
            ceilings.append(float(cap["max_sustainable_qps"]))
        obs = (cap.get("inputs") or {}).get("observed_qps")
        if isinstance(obs, (int, float)):
            observed.append(float(obs))
        if isinstance(cap.get("headroom_frac"), (int, float)):
            headrooms.append(float(cap["headroom_frac"]))
        if cap.get("scale_hint") == "up" and cap.get("headroom_frac") is None:
            burning = True  # burn-only scale signal (no computable ceiling)
        inputs = cap.get("inputs") or {}
        if (
            max(
                inputs.get("error_burn_rate", 0.0) or 0.0,
                inputs.get("latency_burn_rate", 0.0) or 0.0,
            )
            > 1.0
        ):
            burning = True
    total_ceiling = sum(ceilings) if ceilings else None
    total_observed = sum(observed) if observed else None
    min_headroom = min(headrooms) if headrooms else None
    recommended = None
    if ceilings and total_observed is not None:
        import math

        mean_ceiling = total_ceiling / len(ceilings)
        recommended = max(
            1,
            math.ceil(total_observed / (TARGET_UTILIZATION * mean_ceiling)),
        )
        if burning:
            recommended += 1
    scale_hint = "unknown"
    n_active = fleet.active_count()
    if burning or (min_headroom is not None and min_headroom <= 0.0):
        scale_hint = "up"
    elif recommended is not None:
        if recommended < n_active and (
            min_headroom is None or min_headroom > 1.0 - TARGET_UTILIZATION
        ):
            scale_hint = "hold_or_down"
        else:
            scale_hint = "hold"
    return {
        "fleet": {
            "name": fleet.name,
            "replicas": len(per_replica),
            "active": n_active,
            "routable": len(fleet.routable()),
            "per_replica": per_replica,
        },
        "inputs": {
            "observed_qps": (
                round(total_observed, 3) if total_observed is not None else None
            ),
            "replicas_reporting": len(ceilings),
        },
        "ceilings_qps": (
            {"fleet": round(total_ceiling, 3)} if total_ceiling is not None else {}
        ),
        "binding_ceiling": "fleet" if total_ceiling is not None else None,
        "max_sustainable_qps": (
            round(total_ceiling, 3) if total_ceiling is not None else None
        ),
        "headroom_frac": (
            round(min_headroom, 4) if min_headroom is not None else None
        ),
        "recommended_replicas": recommended,
        "scale_hint": scale_hint,
        "target_utilization": TARGET_UTILIZATION,
        "caveats": caveats,
    }
