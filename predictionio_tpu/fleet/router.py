"""The fleet router: a thin CPU-tier HTTP front end in front of N
prediction-server replicas.

Proxies ``POST /queries.json`` with:

- **entity affinity** — the query's joinable entity id (the same fields
  the quality joiner and the canary split key on) picks a home replica by
  rendezvous hashing (:meth:`FleetState.route_order`), so one user lands
  on one replica — any per-user device caches stay warm and the canary
  hash-split (computed from the same entity id inside each replica) is
  coherent fleet-wide;
- **per-replica circuit breakers** — a dead replica costs ~0 ms once its
  breaker opens; /readyz-driven ejection and re-admission ride the
  :class:`~predictionio_tpu.fleet.membership.FleetState` prober;
- **deadline-bounded retry-elsewhere** — a transport failure or a 503
  shed from one replica retries on the next replica in the rendezvous
  order, as long as the request's ``X-Pio-Deadline`` budget has time left
  and the shared :class:`~predictionio_tpu.resilience.retry.RetryBudget`
  has tokens (retries must not amplify an outage);
- **propagation** — ``X-Pio-Request-Id``, ``X-Pio-Trace-Id`` /
  ``X-Pio-Parent-Span`` (the forward runs under a ``fleet.forward`` span,
  so the replica's spans parent under the router hop and ``pio trace``
  shows the extra lane), and ``X-Pio-Deadline`` decremented by the budget
  already spent.  The answering replica is echoed in ``X-Pio-Replica``.

The router also serves ``GET /fleet.json`` (the membership registry), a
fleet-aggregated ``GET /capacity.json`` (sum max-QPS, min headroom, fleet
recommended replicas), a **federated** ``GET /metrics`` (every replica's
families merged with a ``replica`` label — fleet/federation.py; pass
``?local=1`` for the router's own process registry), and a fleet
``GET /alerts.json`` (every replica's firing/pending alerts replica-tagged
next to the router's own) so ``pio capacity --url <router>``,
``pio status --url <router>``, and one Prometheus scrape read the whole
fleet.
"""

from __future__ import annotations

import http.client
import logging
import threading
import time
from typing import Any

from predictionio_tpu.fleet.membership import (
    REPLICA_HEADER,
    FleetState,
    Replica,
    fleet_capacity,
)
from predictionio_tpu.obs.disttrace import propagation_headers
from predictionio_tpu.obs.http import add_observability_routes
from predictionio_tpu.obs.logging import REQUEST_ID_HEADER, get_request_id
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.quality import DEFAULT_ENTITY_FIELDS
from predictionio_tpu.obs.tracing import trace
from predictionio_tpu.resilience.admission import AdmissionController
from predictionio_tpu.resilience.deadline import DEADLINE_HEADER, remaining
from predictionio_tpu.resilience.retry import RetryBudget
from predictionio_tpu.server.httpd import (
    HTTPApp,
    Request,
    Response,
    error_response,
    json_response,
    key_matches,
    shed_response,
)

log = logging.getLogger("predictionio_tpu.fleet")

#: replica response headers the router passes through to the client
_PASSTHROUGH_HEADERS = (
    "X-Pio-Engine-Instance",
    "X-Pio-Variant",
    "X-Pio-App",
    "X-Pio-Shed-Reason",
    "X-Pio-Degraded",
    "Retry-After",
)

#: transport-level failures that trigger retry-elsewhere
_NET_ERRORS = (
    http.client.HTTPException,
    ConnectionError,
    BrokenPipeError,
    TimeoutError,
    OSError,
)


class _ReplicaConnections:
    """Per-thread keep-alive connections to each replica: the router's
    serving threads are long-lived, so re-connecting per forward would pay
    a connect round trip per request."""

    #: drop a keep-alive connection idle longer than this before reuse
    _MAX_IDLE_S = 10.0

    def __init__(self):
        self._local = threading.local()

    def _pool(self) -> dict:
        pool = getattr(self._local, "pool", None)
        if pool is None:
            pool = {}
            self._local.pool = pool
        return pool

    def connection(
        self, replica: Replica, timeout: float
    ) -> http.client.HTTPConnection:
        pool = self._pool()
        entry = pool.get(replica.url)
        now = time.monotonic()
        if entry is not None and now - entry[1] > self._MAX_IDLE_S:
            self.drop(replica)
            entry = None
        if entry is None:
            trimmed = replica.url.split("://", 1)[-1]
            host, _, port = trimmed.partition(":")
            conn = http.client.HTTPConnection(
                host, int(port or 80), timeout=timeout
            )
            pool[replica.url] = (conn, now)
        else:
            conn = entry[0]
            pool[replica.url] = (conn, now)
        conn.timeout = timeout
        sock = getattr(conn, "sock", None)
        if sock is not None:
            sock.settimeout(timeout)
        return conn

    def drop(self, replica: Replica) -> None:
        entry = self._pool().pop(replica.url, None)
        if entry is not None:
            try:
                entry[0].close()
            except OSError:
                pass


def _payload_entity(payload: Any) -> str | None:
    """The joinable entity id of a query payload — the same fields the
    quality joiner and DeployedEngine.payload_entity key on, so router
    affinity, canary split, and feedback joins all agree on who 'the
    user' is."""
    if isinstance(payload, dict):
        for f in DEFAULT_ENTITY_FIELDS:
            v = payload.get(f)
            if v is not None:
                return str(v)
    return None


def _request_app(req: Any) -> str | None:
    """The tenant (app) a request names, if any: X-Pio-App header or
    ``?app=`` query."""
    headers = getattr(req, "headers", None) or {}
    for k, v in headers.items():
        if k.lower() == "x-pio-app" and v:
            return str(v)
    q = getattr(req, "query", None) or {}
    v = q.get("app")
    return str(v) if v else None


def create_router_app(
    fleet: FleetState,
    access_key: str | None = None,
    registry: MetricsRegistry | None = None,
    #: in-flight cap at the router's own admission gate (None = uncapped)
    max_inflight: int | None = None,
    #: default per-request budget, overridable via X-Pio-Deadline
    default_deadline_s: float | None = None,
    #: per-forward socket timeout (always additionally capped by the
    #: remaining deadline budget)
    forward_timeout_s: float = 10.0,
    #: distinct replicas tried per request (first + retries-elsewhere)
    max_attempts: int = 3,
    retry_budget: RetryBudget | None = None,
    autoscaler: Any | None = None,
    on_stop: Any | None = None,
    alerts: Any | None = None,
    incidents: Any | None = None,
) -> HTTPApp:
    """Build the router HTTPApp over a :class:`FleetState`.

    ``alerts`` (an AlertEvaluator over the router's registry — its default
    breaker rule watches the per-replica breakers) and ``incidents`` ride
    onto the observability surface; the federated ``/alerts.json`` always
    aggregates the replicas' evaluators, folding the router's own local
    snapshot in when one is attached."""
    from predictionio_tpu.fleet.federation import (
        FederationCache,
        federated_alerts,
        federated_costs,
        federated_metrics_text,
        scrape_replicas,
    )
    from predictionio_tpu.obs.http import PROMETHEUS_CONTENT_TYPE

    app = HTTPApp("router")
    app.default_deadline_s = default_deadline_s
    if max_inflight is not None:
        app.admission = AdmissionController(
            max_inflight, registry=registry or REGISTRY
        )
    app.fleet = fleet
    app.autoscaler = autoscaler
    reg = registry or REGISTRY
    budget = retry_budget if retry_budget is not None else RetryBudget()
    pool = _ReplicaConnections()

    m_forwards = reg.counter(
        "pio_router_forwards_total",
        "Requests forwarded to replicas, by replica and outcome",
        labelnames=("replica", "outcome"),
    )
    m_retries = reg.counter(
        "pio_router_retry_elsewhere_total",
        "Forwards retried on another replica, by trigger",
        labelnames=("reason",),
    )
    m_forward_seconds = reg.histogram(
        "pio_router_forward_seconds",
        "Router->replica forward latency (successful forwards)",
        labelnames=("replica",),
    )

    def _authorized(req: Request) -> bool:
        return access_key is None or key_matches(req, access_key)

    def _forward_once(
        replica: Replica, req: Request, deadline_left: float | None
    ) -> tuple[int, bytes, dict[str, str]]:
        """One router->replica round trip.  Raises a ``_NET_ERRORS`` member
        on transport failure (the retry-elsewhere trigger)."""
        headers = {"Content-Type": "application/json"}
        rid = get_request_id()
        if rid:
            headers[REQUEST_ID_HEADER] = rid
        headers.update(propagation_headers())
        # the tenant identity travels with the forward: the replica's
        # per-tenant gate needs to know WHO is asking, whichever replica
        # the rendezvous order lands on
        tenant_app = _request_app(req)
        if tenant_app:
            headers["X-Pio-App"] = tenant_app
        timeout = forward_timeout_s
        if deadline_left is not None:
            # decrement the forwarded budget by what this hop already
            # spent, and never sit in a socket past the client's deadline
            headers[DEADLINE_HEADER] = f"{max(deadline_left, 0.001):.6f}"
            timeout = max(min(timeout, deadline_left), 0.001)
        conn = pool.connection(replica, timeout)
        try:
            conn.request("POST", req.path, body=req.body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except _NET_ERRORS:
            pool.drop(replica)
            raise
        return resp.status, data, {k: v for k, v in resp.getheaders()}

    @app.route("POST", "/queries\\.json")
    def queries(req: Request) -> Response:
        try:
            payload = req.json()
            if not isinstance(payload, dict):
                raise ValueError("query must be a JSON object")
        except Exception as e:
            return error_response(400, f"invalid query: {e}")
        # affinity keys on (app, entity): two tenants sharing an entity id
        # space must NOT share per-user cache/canary homes — tenant A's
        # "user1" and tenant B's "user1" are different people
        entity = _payload_entity(payload)
        tenant_app = _request_app(req)
        affinity = (
            f"{tenant_app}|{entity}"
            if tenant_app and entity is not None
            else entity
        )
        order = fleet.route_order(affinity)
        if not order:
            return shed_response("no routable replicas", 1.0)
        last_shed: Response | None = None
        last_error: Exception | None = None
        attempts = 0
        for replica in order:
            if attempts >= max_attempts:
                break
            deadline_left = remaining()
            if deadline_left is not None and deadline_left <= 0:
                break  # the budget died mid-retry: answer 504 below
            br = replica.breaker
            if not br.allow():
                # open breaker: skip in ~0 ms, the next replica in the
                # rendezvous order is this entity's deterministic failover
                continue
            if attempts > 0 and not budget.try_spend():
                # a retry needs a budget token (retries must not amplify
                # an outage); the consumed half-open trial is returned
                br.release_trial()
                m_retries.labels("budget_exhausted").inc()
                break
            attempts += 1
            fleet.note_inflight(replica, +1)
            t0 = time.perf_counter()
            try:
                # the forward runs under its own span so the assembled
                # trace shows the router lane, with the replica's spans
                # parented under this hop (storage.remote's idiom)
                with trace("fleet.forward", record=False, ring=False) as sp:
                    sp.tags = {"replica": replica.replica_id}
                    status, data, rheaders = _forward_once(
                        replica, req, deadline_left
                    )
            except _NET_ERRORS as e:
                br.record_failure()
                fleet.note_forward_failure(replica)
                m_forwards.labels(replica.replica_id, "transport_error").inc()
                m_retries.labels("transport_error").inc()
                last_error = e
                continue
            finally:
                fleet.note_inflight(replica, -1)
            # an HTTP answer means the replica is alive, whatever the code
            br.record_success()
            fleet.note_forward_success(replica)
            if status == 503:
                # the replica shed: its queue/admission is full, not down.
                # Another replica may have room — retry elsewhere inside
                # the deadline budget.
                m_forwards.labels(replica.replica_id, "shed").inc()
                m_retries.labels("shed").inc()
                last_shed = _passthrough(status, data, rheaders, replica)
                continue
            budget.record_call()
            m_forwards.labels(replica.replica_id, "ok").inc()
            m_forward_seconds.labels(replica.replica_id).observe(
                time.perf_counter() - t0
            )
            return _passthrough(status, data, rheaders, replica)
        # every eligible replica failed, shed, or the budget ran out
        deadline_left = remaining()
        if deadline_left is not None and deadline_left <= 0:
            return error_response(
                504, "deadline exceeded while retrying across replicas"
            )
        if last_shed is not None:
            return last_shed
        return shed_response(
            f"no replica answered ({attempts} tried"
            + (f"; last error: {last_error}" if last_error else "")
            + ")",
            1.0,
        )

    def _passthrough(
        status: int, data: bytes, rheaders: dict[str, str], replica: Replica
    ) -> Response:
        resp = Response(
            status,
            data,
            content_type=rheaders.get("Content-Type")
            or rheaders.get("content-type")
            or "application/json; charset=utf-8",
        )
        for name in _PASSTHROUGH_HEADERS:
            v = rheaders.get(name) or rheaders.get(name.lower())
            if v:
                resp.headers[name] = v
        resp.headers[REPLICA_HEADER] = replica.replica_id
        return resp

    # -- fleet surfaces ------------------------------------------------------
    # registered BEFORE add_observability_routes so the fleet-aggregated
    # /capacity.json, /metrics, and /alerts.json win over the
    # process-local ones (first match routes)

    fed_cache = FederationCache()

    @app.route("GET", "/metrics")
    def federated_metrics(req: Request) -> Response:
        """The federated exposition: one scrape sees the fleet.  The
        process-local registry remains reachable via ``?local=1`` (and its
        families are folded into the federation as replica="router")."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        if req.query.get("local") in ("1", "true"):
            reg.history.sample(reg)
            return Response(
                200,
                reg.render_prometheus(),
                content_type=PROMETHEUS_CONTENT_TYPE,
            )

        def build() -> str:
            bodies, errors = scrape_replicas(fleet, "/metrics.json")
            return federated_metrics_text(
                bodies, errors, local_registry=reg, local_label="router"
            )

        return Response(
            200,
            fed_cache.get("metrics", build),
            content_type=PROMETHEUS_CONTENT_TYPE,
        )

    @app.route("GET", "/alerts\\.json")
    def federated_alerts_json(req: Request) -> Response:
        """Every replica's alert state, replica-tagged, in one body (the
        `pio status --url <router>` fold and the dashboard's fleet Alerts
        panel read this)."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")

        def build() -> dict:
            bodies, errors = scrape_replicas(fleet, "/alerts.json")
            return federated_alerts(
                bodies,
                errors,
                local_snapshot=(
                    alerts.snapshot() if alerts is not None else None
                ),
                local_label="router",
            )

        return json_response(200, fed_cache.get("alerts", build))

    @app.route("GET", "/costs\\.json")
    def federated_costs_json(req: Request) -> Response:
        """Every replica's cost ledger in one body: replica-tagged rows
        plus fleet-wide merged per-(app, route, variant) sums — the
        `pio costs --url <router>` and `pio top` fold."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")

        def build() -> dict:
            bodies, errors = scrape_replicas(fleet, "/costs.json")
            local = getattr(app, "costs", None)
            return federated_costs(
                bodies,
                errors,
                local_snapshot=(
                    local.snapshot() if local is not None else None
                ),
                local_label="router",
            )

        return json_response(200, fed_cache.get("costs", build))

    @app.route("GET", "/fleet\\.json")
    def fleet_json(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        fleet.refresh()
        body = fleet.snapshot()
        if autoscaler is not None:
            body["autoscaler"] = autoscaler.snapshot()
        return json_response(200, body)

    @app.route("GET", "/capacity\\.json")
    def capacity_json(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        # serve the cached scrape when fresh: the autoscaler (or a watch)
        # already fans out N replica calls on a cadence, and re-scraping
        # per request would block this handler thread for up to
        # N×probe_timeout on a hung replica
        return json_response(
            200,
            fleet_capacity(
                fleet, scrape=fleet.capacity_scrape_stale(max_age_s=5.0)
            ),
        )

    @app.route("POST", "/fleet/scale")
    def fleet_scale(req: Request) -> Response:
        """Operator override: pin the fleet size (the `pio fleet scale`
        target).  ``?replicas=N`` pins, ``?replicas=auto`` un-pins."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        if autoscaler is None:
            return json_response(
                501, {"message": "no autoscaler attached to this router"}
            )
        raw = req.query.get("replicas", "")
        if raw == "auto":
            autoscaler.set_target(None)
            return json_response(200, {"target": None, "mode": "auto"})
        try:
            n = int(raw)
            if n < 1:
                raise ValueError
        except ValueError:
            return json_response(
                400, {"message": "replicas must be a positive integer or 'auto'"}
            )
        autoscaler.set_target(n)
        return json_response(200, {"target": n, "mode": "pinned"})

    @app.route("POST", "/stop")
    def stop(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        if on_stop is not None:
            threading.Thread(target=on_stop, daemon=True).start()
        return json_response(200, {"message": "Shutting down."})

    def _replicas_routable() -> bool:
        return len(fleet.routable()) > 0

    add_observability_routes(
        app,
        reg,
        access_key=access_key,
        readiness={"replicas_routable": _replicas_routable},
        alerts=alerts,
        incidents=incidents,
    )
    return app
