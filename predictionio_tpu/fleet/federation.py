"""Fleet-wide telemetry federation: one scrape sees the whole fleet.

The PR 11 fleet made N replicas one *routing* domain but left them N
separate *telemetry* domains: a Prometheus scraper (or an operator's
``pio metrics --url``) had to know every replica URL, and an alert firing
on replica 3 was invisible from the router.  This module is the DrJAX-style
fan-in (arxiv 2403.07128's MapReduce-over-fleet idiom, applied to
telemetry): the router aggregates its replicas'

- ``GET /metrics`` — every replica's metric families merged into one
  Prometheus exposition with a ``replica`` label per series (the router's
  own families ride along as ``replica="router"``), plus a synthesized
  ``pio_federation_up{replica}`` gauge so a dead replica is a *visible
  zero*, not a silent absence;
- ``GET /alerts.json`` — per-replica alert evaluator states merged into
  one body: fleet-wide firing/pending totals, every non-ok instance tagged
  with its replica, per-replica summaries, and the router's own local
  alerts.

Scrapes run concurrently with a bounded per-replica timeout, so one dead
replica costs its rows plus a named ``source_errors`` entry — never a
hang.  The router caches each aggregation like its ``/capacity.json``
scrape (:data:`CACHE_TTL_S`), so a tight external scrape loop cannot
amplify into N×QPS internal traffic.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Mapping

from predictionio_tpu.fleet.membership import FleetState
from predictionio_tpu.obs.metrics import (
    MetricsRegistry,
    _fmt,
    _labels_text,
)

#: how long a federated aggregation is served from cache (the same knob as
#: the router's /capacity.json scrape reuse)
CACHE_TTL_S = 5.0

#: per-replica fetch timeout — a dead replica costs one bounded wait
#: (fetches run concurrently, so the total wait is the slowest source)
FETCH_TIMEOUT_S = 3.0


def scrape_replicas(
    fleet: FleetState,
    path: str,
    timeout: float = FETCH_TIMEOUT_S,
) -> tuple[dict[str, Any], dict[str, str]]:
    """Fetch ``path`` from every non-draining replica concurrently.
    Returns ``({replica_id: parsed JSON body}, {replica_id: error})`` — a
    replica that is down, 401s, or answers garbage lands in the error map
    with its reason and is simply absent from the bodies (replica ids
    contain colons, so errors stay structured rather than string-joined)."""
    reps = [r for r in fleet.replicas() if not r.draining]
    bodies: dict[str, Any] = {}
    errors: dict[str, str] = {}
    if not reps:
        return bodies, errors

    def fetch(rep) -> Any:
        headers = {}
        if fleet.access_key:
            headers["Authorization"] = f"Bearer {fleet.access_key}"
        req = urllib.request.Request(rep.url + path, headers=headers)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))

    with ThreadPoolExecutor(
        max_workers=min(len(reps), 8), thread_name_prefix="pio-federate"
    ) as pool:
        futures = [(rep, pool.submit(fetch, rep)) for rep in reps]
        for rep, fut in futures:
            try:
                bodies[rep.replica_id] = fut.result()
            except Exception as e:
                errors[rep.replica_id] = f"{type(e).__name__}: {e}"
    return bodies, errors


# ---------------------------------------------------------------------------
# /metrics federation


def _render_series(
    out: list[str],
    name: str,
    kind: str,
    labels: Mapping[str, str],
    series: Mapping[str, Any],
    bounds: list[float] | None,
) -> None:
    names = tuple(labels)
    values = tuple(str(v) for v in labels.values())
    base = _labels_text(names, values)
    if kind in ("counter", "gauge"):
        v = series.get("value")
        if isinstance(v, (int, float)):
            out.append(f"{name}{base} {_fmt(float(v))}")
        return
    counts = series.get("buckets")
    if not isinstance(counts, list) or bounds is None:
        return
    cum = 0
    for bound, c in zip(list(bounds) + [math.inf], counts):
        try:
            cum += int(c)
        except (TypeError, ValueError):
            return
        le = _labels_text(names + ("le",), values + (_fmt(float(bound)),))
        out.append(f"{name}_bucket{le} {cum}")
    out.append(f"{name}_sum{base} {repr(float(series.get('sum') or 0.0))}")
    out.append(f"{name}_count{base} {int(series.get('count') or 0)}")


def federated_metrics_text(
    bodies: Mapping[str, Mapping[str, Any]],
    errors: Mapping[str, str],
    local_registry: MetricsRegistry | None = None,
    local_label: str = "router",
) -> str:
    """Merge ``/metrics.json`` bodies into ONE Prometheus text exposition,
    every series gaining a ``replica`` label.  The local registry (the
    router's own forwards/retries/latency families) joins under
    ``local_label``; ``pio_federation_up{replica}`` reports 1 per scraped
    replica and 0 per failed one, and failures are also named in comment
    lines so a text-only scrape still shows WHICH source died."""
    merged: dict[str, dict[str, Any]] = {}

    def fold(replica: str, body: Mapping[str, Any]) -> None:
        for name, fam in body.items():
            if not isinstance(fam, Mapping):
                continue
            kind = fam.get("type")
            if kind not in ("counter", "gauge", "histogram"):
                continue
            entry = merged.setdefault(
                name,
                {
                    "type": kind,
                    "help": fam.get("help") or "",
                    "bounds": fam.get("bounds"),
                    "rows": [],
                },
            )
            if entry["type"] != kind:
                continue  # conflicting declarations: first replica wins
            if entry.get("bounds") is None and fam.get("bounds"):
                entry["bounds"] = fam.get("bounds")
            for s in fam.get("series") or ():
                labels = {"replica": replica}
                for k, v in (s.get("labels") or {}).items():
                    k = str(k)
                    if k == "replica":
                        # the router's own per-replica families (e.g.
                        # pio_router_forwards_total{replica=...}) must not
                        # clobber the federation label — the Prometheus
                        # federation idiom: exported_<label>
                        k = "exported_replica"
                    labels[k] = str(v)
                entry["rows"].append((labels, s))

    if local_registry is not None:
        fold(local_label, local_registry.render_json())
    for replica in sorted(bodies):
        fold(replica, bodies[replica])

    out: list[str] = []
    for rid in sorted(errors):
        out.append(f"# federation source error: {rid}: {errors[rid]}")
    out.append(
        "# HELP pio_federation_up Whether the last federated scrape of a "
        "replica succeeded"
    )
    out.append("# TYPE pio_federation_up gauge")
    for replica in sorted(bodies):
        out.append(f'pio_federation_up{{replica="{replica}"}} 1')
    for rid in sorted(errors):
        out.append(f'pio_federation_up{{replica="{rid}"}} 0')
    for name in sorted(merged):
        entry = merged[name]
        out.append(f"# HELP {name} {entry['help']}")
        out.append(f"# TYPE {name} {entry['type']}")
        for labels, series in entry["rows"]:
            _render_series(
                out, name, entry["type"], labels, series, entry.get("bounds")
            )
    return "\n".join(out) + "\n" if out else ""


# ---------------------------------------------------------------------------
# /alerts.json federation


def federated_alerts(
    bodies: Mapping[str, Mapping[str, Any]],
    errors: Mapping[str, str],
    local_snapshot: Mapping[str, Any] | None = None,
    local_label: str = "router",
) -> dict[str, Any]:
    """Merge ``/alerts.json`` bodies into one fleet body: every non-ok
    instance tagged with its replica, fleet-wide firing/pending totals,
    per-replica summaries (None for a replica whose scrape failed — its
    reason is in ``source_errors``), and the most recent transitions
    interleaved newest-first."""
    sources: list[tuple[str, Mapping[str, Any]]] = []
    if local_snapshot is not None:
        sources.append((local_label, local_snapshot))
    sources.extend((rid, bodies[rid]) for rid in sorted(bodies))
    alerts: list[dict[str, Any]] = []
    recent: list[dict[str, Any]] = []
    replicas: dict[str, dict[str, Any] | None] = {}
    for rid, body in sources:
        rows = body.get("alerts") or ()
        replicas[rid] = {
            "firing": int(body.get("firing") or 0),
            "pending": int(body.get("pending") or 0),
            "ticks": body.get("ticks"),
            "last_tick_at": body.get("last_tick_at"),
        }
        for a in rows:
            alerts.append({**a, "replica": rid})
        for e in body.get("recent") or ():
            recent.append({**e, "replica": rid})
    for rid in errors:
        replicas[rid] = None
    alerts.sort(
        key=lambda a: (
            0 if a.get("state") == "firing" else 1,
            -(a.get("age_s") or 0.0),
        )
    )
    recent.sort(key=lambda e: -(e.get("at") or 0.0))
    return {
        "fleet": True,
        "alerts": alerts,
        "firing": sum(1 for a in alerts if a.get("state") == "firing"),
        "pending": sum(1 for a in alerts if a.get("state") == "pending"),
        "recent": recent[:64],
        "replicas": replicas,
        "source_errors": [
            f"{rid}: {errors[rid]}" for rid in sorted(errors)
        ],
    }


# ---------------------------------------------------------------------------
# /costs.json federation


def federated_costs(
    bodies: Mapping[str, Mapping[str, Any]],
    errors: Mapping[str, str],
    local_snapshot: Mapping[str, Any] | None = None,
    local_label: str = "router",
) -> dict[str, Any]:
    """Merge ``/costs.json`` bodies into one fleet body: every replica's
    per-(app, route, variant) total rides replica-tagged in ``totals``
    (``pio costs`` renders them as ``app@replica``), and ``merged`` sums
    the same keys fleet-wide — the substrate a fleet-level quota or the
    ``cost_skew`` question "who costs what, anywhere" reads.  A replica
    whose scrape failed is named in ``source_errors`` and simply absent
    from the rows."""
    from predictionio_tpu.obs.costs import COST_FIELDS

    sources: list[tuple[str, Mapping[str, Any]]] = []
    if local_snapshot is not None:
        sources.append((local_label, local_snapshot))
    sources.extend((rid, bodies[rid]) for rid in sorted(bodies))
    rows: list[dict[str, Any]] = []
    merged: dict[tuple[str, str, str], dict[str, float]] = {}
    replicas: list[str] = []
    budgets: dict[str, Any] = {"per_app": {}, "default_device_s_per_min": None}
    for rid, body in sources:
        replicas.append(rid)
        b = body.get("budgets") or {}
        budgets["per_app"].update(b.get("per_app") or {})
        if budgets["default_device_s_per_min"] is None:
            budgets["default_device_s_per_min"] = b.get(
                "default_device_s_per_min"
            )
        for row in body.get("totals") or ():
            rows.append({**row, "replica": rid})
            key = (
                str(row.get("app", "?")),
                str(row.get("route", "")),
                str(row.get("variant", "")),
            )
            agg = merged.setdefault(key, dict.fromkeys(COST_FIELDS, 0.0))
            for f in COST_FIELDS:
                try:
                    agg[f] += float(row.get(f, 0.0) or 0.0)
                except (TypeError, ValueError):
                    pass
    rows.sort(key=lambda r: -float(r.get("device_s", 0.0) or 0.0))
    merged_rows = [
        {"app": k[0], "route": k[1], "variant": k[2], **agg}
        for k, agg in sorted(
            merged.items(), key=lambda kv: -kv[1]["device_s"]
        )
    ]
    return {
        "fleet": True,
        "replicas": replicas,
        "totals": rows,
        "merged": merged_rows,
        "budgets": budgets,
        "source_errors": {rid: errors[rid] for rid in sorted(errors)},
    }


class FederationCache:
    """One cached aggregation per key, rebuilt at most every
    :data:`CACHE_TTL_S`, with SINGLE-FLIGHT rebuilds — the router's
    serving threads must never fan out N scrapes per external request,
    and k concurrent requests arriving at TTL expiry must run ONE build
    (the followers wait for the builder's result), not k×N internal
    fetches."""

    def __init__(
        self,
        ttl_s: float = CACHE_TTL_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ttl_s = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[float, Any]] = {}
        #: per-key build mutex: held by the one thread rebuilding that key
        self._building: dict[str, threading.Lock] = {}

    def _fresh(self, key: str) -> tuple[bool, Any]:
        hit = self._cache.get(key)
        if hit is not None and self._clock() - hit[0] <= self.ttl_s:
            return True, hit[1]
        return False, None

    def get(self, key: str, build: Callable[[], Any]) -> Any:
        with self._lock:
            fresh, value = self._fresh(key)
            if fresh:
                return value
            gate = self._building.get(key)
            if gate is None:
                gate = self._building[key] = threading.Lock()
        # serialize builds per key OUTSIDE the cache lock (a build fans
        # out HTTP calls); a follower blocks here for at most one build,
        # then finds the builder's fresh entry
        with gate:
            with self._lock:
                fresh, value = self._fresh(key)
                if fresh:
                    return value
            value = build()  # raising leaves no entry: followers rebuild
            with self._lock:
                self._cache[key] = (self._clock(), value)
            return value
