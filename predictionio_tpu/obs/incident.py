"""Black-box incident recorder: preserve the evidence before it rotates.

Every debugging surface the earlier PRs built is a *bounded ring* — the
flight recorder keeps 32 slow + 64 errored requests, the FragmentStore 256
traces, the MetricsHistory 60 samples per series, the SLO window 10
minutes.  By the time an operator reads an alert, the requests that caused
it have usually rotated out.  This module is the flight-data-recorder fix:
on every firing alert transition (obs/alerts.py), snapshot one **forensic
bundle** to disk — metrics + per-series history sparklines, the SLO window,
recent flight entries, the trace-fragment store, a host stack capture,
/hotpath + /capacity + breaker/lifecycle state — *at the moment of the
incident*, crash-safe (unique tmp + ``os.replace``, the RES003 idiom), and
bounded by count/age retention with per-rule rate limiting so an alert
storm cannot fill the disk.

The bundle is ONE JSON file that doubles as a disttrace fragment body
(top-level ``process``/``now``/``spans`` keys), so
``pio trace <id> --file <bundle.json>`` replays the degraded request's
cross-process waterfall offline, long after every involved daemon is gone.
``pio incident list|show|export`` (tools/cli.py) and ``GET
/incidents.json`` / ``GET /incidents/<id>.json`` (obs/http.py) are the
operator surfaces.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping

from predictionio_tpu.obs.disttrace import FRAGMENTS, process_label
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("predictionio_tpu.obs.incident")

#: bundle schema tag (readers refuse unknown majors)
BUNDLE_FORMAT = "pio-incident-bundle/1"

#: default retention: most-recent bundles kept, older ones unlinked
DEFAULT_MAX_COUNT = 32
DEFAULT_MAX_AGE_S = 7 * 86400.0

#: default per-rule floor between bundles (an alert storm must not write
#: one bundle per tick)
DEFAULT_MIN_INTERVAL_S = 60.0


def min_interval_from_env(default: float = DEFAULT_MIN_INTERVAL_S) -> float:
    """``PIO_INCIDENT_MIN_INTERVAL_S`` — per-rule bundle cooldown in
    seconds (default 60).  A rule flapping at evaluator frequency writes at
    most one bundle per cooldown window; the rest only increment
    ``pio_incidents_suppressed_total{rule}``.  Malformed values fall back
    to the default rather than killing server startup."""
    raw = os.environ.get("PIO_INCIDENT_MIN_INTERVAL_S")
    if not raw:
        return default
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return default


def default_incident_dir() -> str:
    """``PIO_INCIDENT_DIR`` or ``$PIO_HOME/incidents`` — shared by the
    serving process (writer) and a co-located dashboard (reader)."""
    explicit = os.environ.get("PIO_INCIDENT_DIR")
    if explicit:
        return explicit
    home = os.environ.get(
        "PIO_HOME", os.path.join(os.path.expanduser("~"), ".predictionio_tpu")
    )
    return os.path.join(home, "incidents")


class IncidentRecorder:
    """Write, retain, and list forensic bundles under one directory.

    ``app`` hands over the per-server state (slo / flight / hotpath /
    quality / lifecycle / admission) exactly like the capacity model reads
    it; everything is optional — a bundle records whatever exists and
    names what didn't in ``missing``.
    """

    def __init__(
        self,
        directory: str | None = None,
        registry: MetricsRegistry | None = None,
        app: Any = None,
        max_count: int = DEFAULT_MAX_COUNT,
        max_age_s: float = DEFAULT_MAX_AGE_S,
        min_interval_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        fragments: Any = None,
        max_traces: int = 16,
        #: burst-capture window for the host-stack section when no
        #: continuous sampler is armed (seconds of evaluator-thread time
        #: per recorded incident)
        stack_burst_s: float = 0.25,
    ):
        self.directory = directory or default_incident_dir()
        self.registry = registry or REGISTRY
        self.app = app
        self.max_count = max(int(max_count), 1)
        self.max_age_s = float(max_age_s)
        self.min_interval_s = (
            min_interval_from_env()
            if min_interval_s is None
            else float(min_interval_s)
        )
        self.max_traces = max_traces
        self.stack_burst_s = float(stack_burst_s)
        self._clock = clock
        self._fragments = fragments if fragments is not None else FRAGMENTS
        self._lock = threading.Lock()
        self._last_by_rule: dict[str, float] = {}
        self._seq = 0
        self._m_recorded = self.registry.counter(
            "pio_incidents_recorded_total",
            "Incident bundles written to disk, by rule",
            labelnames=("rule",),
        )
        self._m_suppressed = self.registry.counter(
            "pio_incidents_suppressed_total",
            "Incident bundles skipped by the per-rule rate limit",
            labelnames=("rule",),
        )

    # -- capture -------------------------------------------------------------

    def _section(
        self,
        bundle: dict[str, Any],
        missing: list[str],
        name: str,
        fn: Callable[[], Any],
    ) -> None:
        """One best-effort bundle section: a failing snapshot names itself
        in ``missing`` instead of losing the whole bundle — partial
        evidence beats none at the exact moment things are broken."""
        try:
            value = fn()
        except Exception as e:
            missing.append(f"{name}: {type(e).__name__}: {e}")
            return
        if value is None:
            missing.append(name)
        else:
            bundle[name] = value

    def capture(
        self, event: Mapping[str, Any], app: Any = None
    ) -> dict[str, Any]:
        """Build one bundle dict (no disk I/O) for an alert event."""
        app = app if app is not None else self.app
        with self._lock:
            self._seq += 1
            seq = self._seq
        rule = str(event.get("rule") or "manual")
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        incident_id = f"inc-{stamp}-{_slug(rule)}-{seq:03d}-{os.getpid()}"
        missing: list[str] = []
        # the fragment store FIRST: it is the fastest-rotating ring, and
        # the trace of the triggering request is the bundle's whole point
        trace_ids = list(self._fragments.trace_ids())[: self.max_traces]
        spans: list[dict[str, Any]] = []
        for tid in trace_ids:
            spans.extend(self._fragments.fragments(tid))
        # a per-tenant alert instance keys as "app=name[,...]": surface the
        # offending tenant as a first-class field so incident triage (and
        # `pio incidents`) names the neighbor without parsing keys
        tenant = None
        key = event.get("key")
        if isinstance(key, str):
            for part in key.split(","):
                if part.startswith("app="):
                    tenant = part[len("app="):]
                    break
        bundle: dict[str, Any] = {
            "format": BUNDLE_FORMAT,
            "id": incident_id,
            "rule": rule,
            "key": event.get("key"),
            "tenant": tenant,
            "severity": event.get("severity"),
            "value": event.get("value"),
            "at": event.get("at") or round(time.time(), 3),
            "event": dict(event),
            # fragment-body superset: `pio trace <id> --file bundle.json`
            # loads this file directly (obs/timeline.load_fragment_file)
            "process": process_label(),
            "pid": os.getpid(),
            "now": round(time.time(), 6),
            "trace_ids": trace_ids,
            "spans": spans,
        }
        self._section(
            bundle, missing, "metrics", self.registry.render_json
        )
        self._section(
            bundle,
            missing,
            "history",
            lambda: self.registry.history.snapshot(),
        )
        slo = getattr(app, "slo", None)
        self._section(
            bundle, missing, "slo",
            (lambda: slo.snapshot()) if slo is not None else lambda: None,
        )
        flight = getattr(app, "flight", None)
        self._section(
            bundle, missing, "flight",
            (lambda: flight.snapshot(limit=16))
            if flight is not None
            else lambda: None,
        )
        hotpath = getattr(app, "hotpath", None)
        self._section(
            bundle, missing, "hotpath",
            (lambda: hotpath.snapshot())
            if hotpath is not None
            else lambda: None,
        )

        def _capacity() -> Any:
            from predictionio_tpu.obs.capacity import capacity_snapshot

            return capacity_snapshot(app, self.registry)

        self._section(bundle, missing, "capacity", _capacity)

        def _breakers() -> Any:
            from predictionio_tpu.resilience.breaker import breaker_states

            return breaker_states() or None

        self._section(bundle, missing, "breakers", _breakers)

        def _stacks() -> Any:
            from predictionio_tpu.obs.sampling import SAMPLER, StackSampler

            # an operator-armed continuous sampler has the richer
            # aggregation: snapshot it.  Otherwise take a bounded BURST
            # with a private sampler and stop it — recording one incident
            # must not leave a permanent 100 Hz profiler running in the
            # serving process (the burst blocks only the evaluator's tick
            # thread, never a request)
            if SAMPLER.running:
                return {
                    "source": "continuous",
                    "summary": SAMPLER.snapshot(),
                    "collapsed": SAMPLER.collapsed(),
                }
            burst = StackSampler(registry=self.registry)
            burst.start()
            try:
                threading.Event().wait(self.stack_burst_s)
            finally:
                burst.stop()
            return {
                "source": f"burst:{self.stack_burst_s}s",
                "summary": burst.snapshot(),
                "collapsed": burst.collapsed(),
            }

        self._section(bundle, missing, "stacks", _stacks)

        def _provenance() -> Any:
            # the breaching answers' decision records: the SLO exemplars'
            # request ids joined against the provenance ring, so the
            # bundle can say WHY those requests answered what they did
            # (and `pio replay-request --record` can re-execute them)
            prov = getattr(app, "provenance", None)
            if prov is None:
                return None
            records = []
            for ex in (bundle.get("slo") or {}).get("exemplars") or []:
                rid = ex.get("request_id")
                rec = prov.get(rid) if rid else None
                if rec is not None:
                    records.append(rec)
            return {"records": records} if records else None

        self._section(bundle, missing, "provenance", _provenance)
        lifecycle = getattr(app, "lifecycle", None)
        self._section(
            bundle, missing, "lifecycle",
            (lambda: lifecycle.snapshot())
            if lifecycle is not None
            else lambda: None,
        )
        # the exemplar: which trace `pio incident show` renders. Breach
        # exemplars first (they point AT the breaching request), then the
        # newest errored flight entry, then the newest trace at all.
        exemplar = None
        for ex in (bundle.get("slo") or {}).get("exemplars") or []:
            if ex.get("trace_id") in trace_ids:
                exemplar = ex["trace_id"]
                break
        if exemplar is None:
            for entry in (bundle.get("flight") or {}).get("errors") or []:
                if entry.get("trace_id") in trace_ids:
                    exemplar = entry["trace_id"]
                    break
        if exemplar is None and trace_ids:
            exemplar = trace_ids[0]
        bundle["exemplar_trace_id"] = exemplar
        bundle["missing"] = missing
        return bundle

    # -- persistence ---------------------------------------------------------

    def record(
        self, event: Mapping[str, Any], app: Any = None
    ) -> str | None:
        """Capture + write one bundle; returns its path, or None when the
        per-rule rate limit suppressed it.  Never raises (the evaluator
        calls this from its tick)."""
        rule = str(event.get("rule") or "manual")
        now = self._clock()
        with self._lock:
            last = self._last_by_rule.get(rule)
            if last is not None and now - last < self.min_interval_s:
                suppress = True
            else:
                self._last_by_rule[rule] = now
                suppress = False
        if suppress:
            self._m_suppressed.labels(rule).inc()
            return None
        try:
            bundle = self.capture(event, app=app)
            path = self._write(bundle)
        except Exception:
            log.exception("incident bundle write failed (rule=%s)", rule)
            return None
        self._m_recorded.labels(rule).inc()
        log.warning(
            "incident bundle recorded: %s (rule=%s, %d spans, %d traces)",
            path,
            rule,
            len(bundle.get("spans") or ()),
            len(bundle.get("trace_ids") or ()),
            extra={"incident_id": bundle["id"], "rule": rule},
        )
        self.prune()
        return path

    def _write(self, bundle: Mapping[str, Any]) -> str:
        """Crash-safe publish: serialize, write to a per-writer unique tmp
        name, fsync, ``os.replace`` — a SIGKILL mid-write leaves no
        half-bundle under the published name."""
        os.makedirs(self.directory, exist_ok=True)
        final = os.path.join(self.directory, f"{bundle['id']}.json")
        tmp = f"{final}.tmp-{os.getpid()}-{threading.get_ident()}"
        data = json.dumps(bundle, sort_keys=True, default=str)
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, final)
        return final

    def prune(self) -> int:
        """Apply count/age retention over the directory; returns bundles
        removed.  Retention is by the published files, not in-memory state,
        so multiple writers (or a restart) converge on the same bound."""
        try:
            entries = _bundle_files(self.directory)
        except OSError:
            return 0
        removed = 0
        now = time.time()
        keep = entries[: self.max_count]
        drop = entries[self.max_count:]
        for path, mtime in keep:
            if self.max_age_s > 0 and now - mtime > self.max_age_s:
                drop.append((path, mtime))
        for path, _ in drop:
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        return removed

    # -- reads ---------------------------------------------------------------

    def list(self) -> list[dict[str, Any]]:
        return list_incidents(self.directory)

    def get_path(self, incident_id: str) -> str | None:
        return find_bundle(self.directory, incident_id)

    def snapshot(self) -> dict[str, Any]:
        """The ``/incidents.json`` body."""
        incidents = self.list()
        return {
            "dir": self.directory,
            "count": len(incidents),
            "max_count": self.max_count,
            "max_age_s": self.max_age_s,
            "min_interval_s": self.min_interval_s,
            "incidents": incidents,
        }


def _slug(name: str) -> str:
    return "".join(c if c.isalnum() else "-" for c in name)[:40] or "alert"


def _bundle_files(directory: str) -> list[tuple[str, float]]:
    """(path, mtime) of every published bundle, newest first."""
    out = []
    for name in os.listdir(directory):
        if not (name.startswith("inc-") and name.endswith(".json")):
            continue
        path = os.path.join(directory, name)
        try:
            out.append((path, os.stat(path).st_mtime))
        except OSError:
            continue
    out.sort(key=lambda e: e[1], reverse=True)
    return out


def list_incidents(directory: str) -> list[dict[str, Any]]:
    """Summaries of every bundle in a directory, newest first — shared by
    the recorder, ``/incidents.json``, and ``pio incident list`` reading a
    directory with no server running."""
    try:
        files = _bundle_files(directory)
    except OSError:
        return []
    out = []
    for path, mtime in files:
        row: dict[str, Any] = {
            "path": path,
            "bytes": 0,
            "mtime": round(mtime, 3),
        }
        try:
            row["bytes"] = os.stat(path).st_size
            with open(path, "r", encoding="utf-8") as f:
                bundle = json.load(f)
            row.update(
                {
                    "id": bundle.get("id"),
                    "rule": bundle.get("rule"),
                    "key": bundle.get("key"),
                    "severity": bundle.get("severity"),
                    "value": bundle.get("value"),
                    "at": bundle.get("at"),
                    "exemplar_trace_id": bundle.get("exemplar_trace_id"),
                    "spans": len(bundle.get("spans") or ()),
                    "missing": bundle.get("missing") or [],
                }
            )
        except (OSError, ValueError) as e:
            row["error"] = f"{type(e).__name__}: {e}"
            row.setdefault(
                "id", os.path.splitext(os.path.basename(path))[0]
            )
        out.append(row)
    return out


def find_bundle(directory: str, incident_id: str) -> str | None:
    """Resolve an id (or unique prefix) to a bundle path."""
    try:
        files = _bundle_files(directory)
    except OSError:
        return None
    exact = os.path.join(directory, f"{incident_id}.json")
    for path, _ in files:
        if path == exact:
            return path
    matches = [
        p
        for p, _ in files
        if os.path.basename(p).startswith(incident_id)
    ]
    return matches[0] if len(matches) == 1 else None


def load_bundle(path: str) -> dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or not str(
        bundle.get("format", "")
    ).startswith("pio-incident-bundle/"):
        raise ValueError(f"{path}: not an incident bundle")
    return bundle


def bundle_timeline(bundle: Mapping[str, Any], trace_id: str | None = None):
    """Assemble the bundle's recorded fragments into a Timeline for one
    trace (default: the exemplar).  Returns None when the bundle holds no
    fragments for it."""
    from predictionio_tpu.obs.timeline import TraceAssemblyError, assemble

    tid = trace_id or bundle.get("exemplar_trace_id")
    if not tid:
        return None
    body = {
        "process": bundle.get("process"),
        "spans": bundle.get("spans") or [],
        "_source": str(bundle.get("id") or "bundle"),
        "_offset_s": 0.0,
    }
    try:
        return assemble([body], str(tid))
    except TraceAssemblyError:
        return None


def render_incident_text(bundle: Mapping[str, Any]) -> str:
    """`pio incident show`: the bundle's story on one screen — what fired,
    what the SLO window looked like, which breakers were open, what was
    missing, then the exemplar request's waterfall rendered OFFLINE from
    the recorded fragments."""
    lines = [
        f"incident {bundle.get('id')}",
        f"rule:      {bundle.get('rule')}"
        + (f"{{{bundle['key']}}}" if bundle.get("key") else "")
        + f"  severity={bundle.get('severity')}  value={bundle.get('value')}",
        f"at:        {_fmt_wall(bundle.get('at'))}",
    ]
    ev = bundle.get("event") or {}
    if ev.get("description"):
        lines.append(f"why:       {ev['description']}")
    slo = bundle.get("slo")
    if slo:
        lines.append(
            f"slo:       {slo.get('status')} — availability "
            f"{slo.get('availability')}, error burn "
            f"{slo.get('error_burn_rate')}, latency burn "
            f"{slo.get('latency_burn_rate')} over {slo.get('requests')} "
            "requests"
        )
    for name, br in sorted((bundle.get("breakers") or {}).items()):
        if br.get("state") != "closed":
            lines.append(
                f"breaker:   {name} {br.get('state').upper()} "
                f"({br.get('failures')} failures)"
            )
    cap = bundle.get("capacity")
    if cap and cap.get("headroom_frac") is not None:
        lines.append(
            f"capacity:  headroom {cap['headroom_frac']:.1%}, "
            f"scale hint {cap.get('scale_hint')}"
        )
    flight = bundle.get("flight") or {}
    errors = flight.get("errors") or []
    if errors:
        lines.append(f"flight:    {len(errors)} errored request(s) recorded:")
        for entry in errors[:5]:
            err = entry.get("error") or entry.get("degraded") or ""
            lines.append(
                f"  {entry.get('status')} {entry.get('method')} "
                f"{entry.get('path')} rid={entry.get('request_id')}"
                + (f" err={str(err)[:80]}" if err else "")
            )
    stacks = (bundle.get("stacks") or {}).get("summary") or {}
    if stacks:
        lines.append(
            f"stacks:    {stacks.get('samples', 0)} samples across "
            f"{len(stacks.get('threads') or {})} thread role(s)"
        )
    prov = (bundle.get("provenance") or {}).get("records") or []
    if prov:
        lines.append(
            f"decisions: {len(prov)} breaching answer(s) with provenance "
            "(replay offline: pio replay-request <rid> --record "
            "<bundle.json> after exporting)"
        )
        for rec in prov[:5]:
            lines.append(
                f"  rid={rec.get('request_id')} "
                f"generation={rec.get('instance_id')} "
                f"variant={rec.get('variant')}"
                + (
                    f" degraded={','.join(rec['degraded'])}"
                    if rec.get("degraded")
                    else ""
                )
            )
    lines.append(
        f"traces:    {len(bundle.get('trace_ids') or ())} trace(s), "
        f"{len(bundle.get('spans') or ())} recorded fragment(s)"
    )
    missing = bundle.get("missing") or []
    if missing:
        lines.append("missing:   " + ", ".join(str(m) for m in missing))
    tl = bundle_timeline(bundle)
    if tl is not None:
        lines.append("")
        lines.append(
            f"exemplar waterfall ({bundle.get('exemplar_trace_id')}) — "
            "replay any recorded trace with: pio trace <id> --file "
            "<bundle.json>"
        )
        lines.append(tl.render_text())
    return "\n".join(lines)


def _fmt_wall(ts: Any) -> str:
    if not isinstance(ts, (int, float)):
        return str(ts)
    return time.strftime("%Y-%m-%d %H:%M:%SZ", time.gmtime(ts)) + f" ({ts})"
