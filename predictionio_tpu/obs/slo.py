"""Rolling-window SLO tracking with burn-rate computation.

Feeds the pager-facing surface: ``GET /healthz`` (process liveness, always
ungated so load balancers can probe), ``GET /readyz`` (dependency checks —
model loaded, batcher not draining, stores reachable), and ``GET /slo.json``
(availability + latency objectives over a rolling window, with burn rates).

Burn rate is the SRE-workbook number: observed bad-fraction divided by the
error budget (1 - target).  1.0 means the budget burns exactly as fast as it
accrues; a sustained rate above 1 means the objective will be missed — the
tracker flags the window "degraded" past :data:`DEGRADED_BURN_RATE`.

The window is a ring of coarse time buckets (default 60 × 10 s): ``record``
is O(1) under one lock, ``snapshot`` sums at most ``len(ring)`` buckets, and
idle buckets age out without a background thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Mapping

#: a window is "degraded" when either burn rate crosses this
DEGRADED_BURN_RATE = 1.0

#: SLO-breach exemplars retained (newest evict oldest)
EXEMPLAR_CAPACITY = 16


def _now() -> float:
    """Monotonic clock — module-level so tests can freeze it."""
    return time.monotonic()


class SLOTracker:
    """Availability + latency SLO over a rolling bucketed window.

    - availability objective: fraction of requests answering below 500
      must be >= ``availability_target``;
    - latency objective: fraction of requests faster than
      ``latency_threshold_s`` must be >= ``latency_target``.
    """

    def __init__(
        self,
        window_s: float = 600.0,
        bucket_s: float = 10.0,
        availability_target: float = 0.999,
        latency_threshold_s: float = 0.5,
        latency_target: float = 0.99,
    ):
        if window_s < bucket_s:
            raise ValueError("window_s must cover at least one bucket")
        self.window_s = float(window_s)
        self.bucket_s = float(bucket_s)
        self.availability_target = availability_target
        self.latency_threshold_s = latency_threshold_s
        self.latency_target = latency_target
        self._lock = threading.Lock()
        n = int(window_s / bucket_s)
        #: ring of [bucket_index, total, errors, slow]
        self._buckets: list[list[float]] = [[-1, 0, 0, 0] for _ in range(n)]
        #: trace-id exemplars of recent SLO-breaching requests — the jump
        #: from "p99 moved" straight to ONE assembled cross-process trace
        self._exemplars: deque[dict[str, Any]] = deque(
            maxlen=EXEMPLAR_CAPACITY
        )
        self._started = _now()

    def record(
        self,
        ok: bool,
        duration_s: float,
        trace_id: str | None = None,
        request_id: str | None = None,
    ) -> None:
        idx = int(_now() / self.bucket_s)
        slot = self._buckets[idx % len(self._buckets)]
        slow = duration_s > self.latency_threshold_s
        with self._lock:
            if slot[0] != idx:  # ring slot holds an expired window: reset
                slot[0], slot[1], slot[2], slot[3] = idx, 0, 0, 0
            slot[1] += 1
            if not ok:
                slot[2] += 1
            if slow:
                slot[3] += 1
            if trace_id and (slow or not ok):
                exemplar = {
                    "trace_id": trace_id,
                    "reason": "error" if not ok else "slow",
                    "duration_s": round(duration_s, 6),
                    "ts": round(time.time(), 3),
                }
                if request_id:
                    # the join key incident bundles use to embed the
                    # breaching answers' provenance records
                    exemplar["request_id"] = request_id
                self._exemplars.append(exemplar)

    def _window_counts(self) -> tuple[int, int, int]:
        horizon = int(_now() / self.bucket_s) - len(self._buckets)
        total = errors = slow = 0
        with self._lock:
            for idx, t, e, s in self._buckets:
                if idx > horizon:
                    total += int(t)
                    errors += int(e)
                    slow += int(s)
        return total, errors, slow

    @staticmethod
    def _burn_rate(bad: int, total: int, target: float) -> float:
        if total == 0:
            return 0.0
        budget = 1.0 - target
        if budget <= 0:
            return float("inf") if bad else 0.0
        return (bad / total) / budget

    def snapshot(self) -> dict[str, Any]:
        total, errors, slow = self._window_counts()
        availability = 1.0 if total == 0 else 1.0 - errors / total
        latency_ok = 1.0 if total == 0 else 1.0 - slow / total
        error_burn = self._burn_rate(errors, total, self.availability_target)
        latency_burn = self._burn_rate(slow, total, self.latency_target)
        degraded = max(error_burn, latency_burn) > DEGRADED_BURN_RATE
        with self._lock:
            exemplars = list(self._exemplars)[::-1]
        return {
            "exemplars": exemplars,
            "window_s": self.window_s,
            "requests": total,
            "errors": errors,
            "slow_requests": slow,
            "availability": round(availability, 6),
            "availability_target": self.availability_target,
            "latency_threshold_s": self.latency_threshold_s,
            "latency_ok_ratio": round(latency_ok, 6),
            "latency_target": self.latency_target,
            "error_burn_rate": round(error_burn, 4),
            "latency_burn_rate": round(latency_burn, 4),
            "status": "degraded" if degraded else "ok",
            "uptime_s": round(_now() - self._started, 3),
        }

    def healthz(self) -> dict[str, Any]:
        """Liveness: the process answers, full stop.  SLO state rides along
        as an advisory field but never flips liveness — restart loops from
        a burning error budget would only make the outage worse."""
        return {
            "status": "alive",
            "uptime_s": round(_now() - self._started, 3),
            "slo_status": self.snapshot()["status"],
        }


def run_readiness(
    checks: Mapping[str, Callable[[], bool]]
) -> tuple[bool, dict[str, bool]]:
    """Evaluate readiness checks; a raising check counts as not ready."""
    results: dict[str, bool] = {}
    for name, fn in checks.items():
        try:
            results[name] = bool(fn())
        except Exception:
            results[name] = False
    return all(results.values()), results
