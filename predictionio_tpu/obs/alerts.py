"""Alert rules engine: the watch loop over everything the earlier PRs meter.

PRs 1-12 made the system measurable — metrics families, SLO burn rates,
breaker states, drift detectors, straggler boards, capacity headroom — but
every signal is pull-only: an operator must be scraping the right endpoint
at the right moment to see an SLO burn or an open breaker.  This module
turns that instrumentation into autonomous detection:

- :class:`AlertRule` — a declarative condition over one *signal selector*
  (a metric family, an SLO burn rate, a breaker state, the capacity
  headroom), with a threshold + direction, a ``for_s`` duration the
  condition must hold before firing (one noisy tick must not page), and a
  ``clear_band`` hysteresis so a value oscillating around the threshold
  doesn't flap fire/resolve;
- :class:`AlertEvaluator` — a clock-injectable daemon (the
  LifecycleController idiom: a thread around a test-drivable
  :meth:`~AlertEvaluator.tick`) running every rule against the current
  signals; each distinct label set of a selector gets its OWN
  ok → pending → firing → resolved state machine, so "breaker open" names
  *which* breaker;
- **sinks** — every firing/resolved transition goes to the structured log
  (always), plus optional webhook POSTs (bounded retry) and a file sink
  (JSON lines; what tests assert against), and to the
  :class:`~predictionio_tpu.obs.incident.IncidentRecorder` which snapshots
  a forensic bundle to disk *before* the bounded rings rotate the evidence
  away;
- a built-in :func:`default_rule_pack` covering the failure modes the
  earlier PRs made detectable, extendable/replaceable via
  ``PIO_ALERT_RULES`` (inline JSON or ``@file``).

The evaluator runs entirely on the cheap CPU side — one pass of dict
arithmetic per tick, self-metered in ``pio_alert_eval_seconds`` — and never
touches the accelerator hot path: rules read *already-collected* state, a
tick takes microseconds, and a raising sink is swallowed (alerting must
never break serving).

``GET /alerts.json`` (obs/http.py, debug-gated) serves the live state; the
fleet router aggregates it replica-labeled (fleet/federation.py) so one
scrape watches the whole fleet.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Mapping

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("predictionio_tpu.obs.alerts")

#: alert severities, mild to pager-worthy ("critical" flips `pio status`
#: --url to exit 1 when firing)
SEVERITIES = ("info", "warning", "critical")

#: instance states (the transitions counter's ``to`` label values)
OK, PENDING, FIRING = "ok", "pending", "firing"

#: numeric breaker states for threshold rules (closed < half_open < open)
_BREAKER_LEVELS = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


@dataclass
class AlertRule:
    """One declarative alert condition.

    ``selector`` names the signal:

    - ``metric:<family>`` — every series of a registry family (counters and
      gauges; each label set is evaluated independently and keys its own
      alert instance).  ``rate=True`` evaluates the per-second delta
      between ticks instead of the raw value — the only useful shape for
      monotonic counters;
    - ``slo.error_burn_rate`` / ``slo.latency_burn_rate`` /
      ``slo.max_burn_rate`` — the app's SLO tracker;
    - ``breaker.state`` — every registered circuit breaker
      (closed=0, half-open=1, open=2), keyed by endpoint;
    - ``capacity.headroom_frac`` — the capacity model's headroom (absent
      until the model has a computable ceiling, so a cold process can't
      false-fire a "no headroom" alert);
    - ``costs.burn_vs_budget`` / ``costs.device_share`` — the app's cost
      ledger (obs/costs.py), keyed per app: device-seconds/min over the
      configured budget, and each app's fraction of attributed device
      time (silent below two active apps).

    ``labels`` filters metric selectors to series whose labels contain the
    given items.  The condition is ``value > threshold`` (direction
    "above") or ``value < threshold`` ("below"); once firing, it resolves
    only when the value crosses back past ``threshold ∓ clear_band`` — the
    hysteresis half of the flap protection (``for_s`` is the other half).
    """

    name: str
    selector: str
    threshold: float
    direction: str = "above"
    for_s: float = 0.0
    clear_band: float = 0.0
    severity: str = "warning"
    rate: bool = False
    labels: dict[str, str] = field(default_factory=dict)
    description: str = ""

    def __post_init__(self):
        if self.direction not in ("above", "below"):
            raise ValueError(
                f"rule {self.name!r}: direction must be above|below"
            )
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity must be one of {SEVERITIES}"
            )
        if self.clear_band < 0 or self.for_s < 0:
            raise ValueError(
                f"rule {self.name!r}: for_s/clear_band must be >= 0"
            )

    def breached(self, value: float) -> bool:
        if self.direction == "above":
            return value > self.threshold
        return value < self.threshold

    def cleared(self, value: float) -> bool:
        """The hysteresis exit: the value must cross the clear band, not
        merely dip back across the threshold."""
        if self.direction == "above":
            return value <= self.threshold - self.clear_band
        return value >= self.threshold + self.clear_band

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "selector": self.selector,
            "threshold": self.threshold,
            "direction": self.direction,
            "for_s": self.for_s,
            "clear_band": self.clear_band,
            "severity": self.severity,
            "rate": self.rate,
            "labels": dict(self.labels),
            "description": self.description,
        }


def default_rule_pack() -> list[AlertRule]:
    """The built-in pack: one rule per failure mode the earlier PRs made
    detectable.  Thresholds follow each subsystem's own semantics (burn
    rate 1.0 = budget burning exactly as fast as it accrues, drift state
    2 = 'drifting' past patience + hysteresis, headroom 0.1 = the capacity
    model's last 10%); ``for_s`` defaults lean conservative — two default
    ticks — because a page that resolves itself before a human looks is
    pure alarm fatigue."""
    return [
        AlertRule(
            "slo_burn", "slo.max_burn_rate", 1.0, for_s=10.0,
            clear_band=0.2, severity="critical",
            description="SLO error budget burning faster than it accrues",
        ),
        AlertRule(
            "breaker_open", "breaker.state", 1.5, for_s=0.0,
            severity="critical",
            description="a circuit breaker is OPEN: a dependency is being "
            "routed around",
        ),
        AlertRule(
            "model_drift", "metric:pio_drift_state", 1.5, for_s=0.0,
            severity="warning",
            description="a feature distribution is 'drifting' past the "
            "detector's patience",
        ),
        AlertRule(
            "recompile_storm", "metric:pio_recompile_storm_total", 0.0,
            rate=True, for_s=0.0, severity="warning",
            description="traffic is churning jit shapes; waves are paying "
            "XLA compiles",
        ),
        AlertRule(
            "shard_straggler", "metric:pio_shard_straggler_total", 0.0,
            rate=True, for_s=0.0, severity="warning",
            description="one device is persistently slowest past the skew "
            "threshold",
        ),
        AlertRule(
            "low_headroom", "capacity.headroom_frac", 0.1,
            direction="below", for_s=10.0, clear_band=0.05,
            severity="warning",
            description="capacity model reports <10% headroom to the "
            "binding ceiling",
        ),
        AlertRule(
            "factor_cache_collapse", "metric:pio_factor_cache_hit_rate",
            0.1, direction="below", for_s=30.0, clear_band=0.05,
            severity="warning",
            description="device factor-cache hit rate collapsed: repeat "
            "users are paying the host gather again",
        ),
        AlertRule(
            "queue_shed", "metric:pio_shed_total", 1.0, rate=True,
            for_s=10.0, clear_band=0.5, severity="warning",
            description="sustained load shedding: requests are being "
            "rejected at admission",
        ),
        AlertRule(
            "ingest_shed", "metric:pio_shed_total", 0.5, rate=True,
            for_s=10.0, clear_band=0.3, severity="warning",
            labels={"reason": "eventstore"},
            description="event ingest is shedding 503s: the event-store "
            "write queue is saturated (compaction backlog or a slow/"
            "degraded storage daemon)",
        ),
        AlertRule(
            "cost_burn", "costs.burn_vs_budget", 1.0, for_s=10.0,
            clear_band=0.2, severity="warning",
            description="an app is burning device-seconds faster than its "
            "configured budget (PIO_COST_BUDGETS) accrues",
        ),
        AlertRule(
            "cost_skew", "costs.device_share", 0.75, for_s=10.0,
            clear_band=0.1, severity="warning",
            description="one app is consuming >75% of this process's "
            "attributed device time: a noisy tenant is starving the rest",
        ),
        AlertRule(
            "freshness_lag",
            "metric:pio_event_visibility_lag_p99_seconds", 60.0,
            for_s=15.0, clear_band=10.0, severity="warning",
            description="event-ack to scan-visible (compaction fold) p99 "
            "lag is over a minute: the freshness SLO input is degrading "
            "(compaction stalled or backlogged)",
        ),
        AlertRule(
            "tenant_quota_shed_rate", "metric:pio_tenant_shed_total", 1.0,
            rate=True, for_s=10.0, clear_band=0.5, severity="warning",
            labels={"reason": "tenant_quota"},
            description="a tenant is being shed at its quota gate faster "
            "than 1 req/s sustained: a noisy neighbor is flooding (each "
            "firing instance carries the offending app label)",
        ),
        AlertRule(
            "tenant_hbm_overcommit",
            "metric:pio_tenant_hbm_refused_total", 0.0, rate=True,
            for_s=0.0, severity="warning",
            description="the HBM bin-packer refused a tenant residency "
            "admission: the replica's device-memory budget is overcommitted "
            "(the firing instance's app label names the refused tenant)",
        ),
    ]


def rules_from_env(
    env: Mapping[str, str] | None = None,
) -> list[AlertRule] | None:
    """Custom rules from ``PIO_ALERT_RULES`` (inline JSON array or
    ``@/path/to/rules.json``); None when unset.  A malformed plan raises —
    silently dropping an operator's alert rules would fake a quiet fleet.
    ``PIO_ALERT_DEFAULT_PACK=0`` drops the built-in pack (custom rules
    otherwise extend it)."""
    e = env if env is not None else os.environ
    raw = e.get("PIO_ALERT_RULES")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], "r", encoding="utf-8") as f:
            raw = f.read()
    plan = json.loads(raw)
    if not isinstance(plan, list):
        raise ValueError("PIO_ALERT_RULES must be a JSON array of rules")
    return [AlertRule(**r) for r in plan]


def resolve_rules(env: Mapping[str, str] | None = None) -> list[AlertRule]:
    """The rule set a server starts with: the default pack (unless
    ``PIO_ALERT_DEFAULT_PACK`` disables it) plus any env/file rules."""
    e = env if env is not None else os.environ
    rules: list[AlertRule] = []
    if e.get("PIO_ALERT_DEFAULT_PACK", "1").lower() not in (
        "0", "off", "false", "no",
    ):
        rules.extend(default_rule_pack())
    extra = rules_from_env(e)
    if extra:
        have = {r.name for r in rules}
        for r in extra:
            if r.name in have:  # same-named env rule overrides the pack's
                rules = [p for p in rules if p.name != r.name]
            rules.append(r)
    return rules


# ---------------------------------------------------------------------------
# sinks


def log_sink(event: Mapping[str, Any]) -> None:
    """The always-on sink: one structured log line per transition."""
    level = (
        logging.WARNING if event.get("event") == FIRING else logging.INFO
    )
    log.log(
        level,
        "alert %s %s (rule=%s key=%s value=%s severity=%s)",
        event.get("event"),
        event.get("rule"),
        event.get("rule"),
        event.get("key"),
        event.get("value"),
        event.get("severity"),
        extra={"alert": dict(event)},
    )


class FileSink:
    """Append transitions as JSON lines — the test-friendly sink, and a
    poor-man's durable alert log for air-gapped deploys."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def __call__(self, event: Mapping[str, Any]) -> None:
        line = json.dumps(dict(event), sort_keys=True, default=str)
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")


class WebhookSink:
    """POST each transition to a webhook URL with bounded retry.  Failures
    are counted and logged, never raised — a dead webhook endpoint must not
    take the evaluator (or worse, a request thread) down with it."""

    def __init__(
        self,
        url: str,
        retries: int = 2,
        timeout_s: float = 3.0,
        backoff_s: float = 0.2,
        registry: MetricsRegistry | None = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.url = url
        self.retries = max(int(retries), 0)
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        self._sleep = sleep
        reg = registry or REGISTRY
        self._m_errors = reg.counter(
            "pio_alerts_sink_errors_total",
            "Alert sink deliveries that exhausted their retries",
            labelnames=("sink",),
        )

    def __call__(self, event: Mapping[str, Any]) -> None:
        import urllib.request

        body = json.dumps(dict(event), default=str).encode("utf-8")
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                with urllib.request.urlopen(
                    req, timeout=self.timeout_s
                ) as r:
                    r.read()
                return
            except Exception as e:  # refused / timeout / HTTP error
                last = e
                if attempt < self.retries:
                    self._sleep(self.backoff_s * (attempt + 1))
        self._m_errors.labels("webhook").inc()
        log.warning("alert webhook %s failed: %s", self.url, last)


# ---------------------------------------------------------------------------
# the evaluator


class _Instance:
    """Per-(rule, key) state-machine record; guarded by the evaluator's
    lock."""

    __slots__ = ("state", "since", "fired_at", "value", "seen_tick")

    def __init__(self):
        self.state = OK
        self.since: float | None = None  # condition first true (monotonic)
        self.fired_at: float | None = None  # wall clock, for display
        self.value: float | None = None
        self.seen_tick = 0


class AlertEvaluator:
    """Evaluate :class:`AlertRule` s on a clock-injectable cadence.

    ``app`` (optional) supplies the non-registry signals the same way the
    capacity model reads them: ``app.slo``, ``app.quality`` (its drift
    gauges are refreshed at tick start so ``metric:pio_drift_state`` is
    current), ``app.admission`` / ``app.microbatcher`` for the capacity
    join.  ``incidents`` (an
    :class:`~predictionio_tpu.obs.incident.IncidentRecorder`) gets a
    forensic-bundle callback on every firing transition.

    ``start()`` runs the daemon thread; tests drive :meth:`tick` with a
    frozen clock (the LifecycleController idiom).
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        rules: Iterable[AlertRule] | None = None,
        app: Any = None,
        interval_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
        sinks: Iterable[Callable[[Mapping[str, Any]], None]] | None = None,
        incidents: Any = None,
        max_events: int = 256,
    ):
        self.registry = registry or REGISTRY
        self.rules = list(rules) if rules is not None else resolve_rules()
        self.app = app
        self.interval_s = float(interval_s)
        self._clock = clock
        self.sinks: list[Callable[[Mapping[str, Any]], None]] = [log_sink]
        if sinks:
            self.sinks.extend(sinks)
        self.incidents = incidents
        self._lock = threading.Lock()
        self._instances: dict[tuple[str, str], _Instance] = {}
        #: previous counter sightings for rate selectors, keyed PER RULE:
        #: (rule, family, labelvalues) -> (value, monotonic_ts) — two rate
        #: rules watching the same family must not share bookkeeping (the
        #: first would zero the second's delta every tick)
        self._prev_counts: dict[
            tuple[str, str, tuple[str, ...]], tuple[float, float]
        ] = {}
        self._events: deque[dict[str, Any]] = deque(maxlen=max_events)
        self._ticks = 0
        self._tick_seconds = 0.0
        self._last_tick_wall: float | None = None
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        self._stopping = False
        reg = self.registry
        self._m_firing = reg.gauge(
            "pio_alerts_firing",
            "Currently-firing alert instances per rule",
            labelnames=("rule",),
        )
        self._m_transitions = reg.counter(
            "pio_alerts_transitions_total",
            "Alert state transitions, by rule and destination state",
            labelnames=("rule", "to"),
        )
        self._m_eval = reg.histogram(
            "pio_alert_eval_seconds",
            "Wall time of one evaluator tick (the watch loop's own cost)",
        )

    # -- signal resolution ---------------------------------------------------

    def _metric_values(
        self, rule: AlertRule, now: float
    ) -> dict[str, float]:
        fam = self.registry.get(rule.selector[len("metric:"):])
        if fam is None or fam.kind == "histogram":
            return {}
        want = rule.labels
        out: dict[str, float] = {}
        for lv, child in fam.series():
            if want:
                have = dict(zip(fam.labelnames, lv))
                if any(have.get(k) != v for k, v in want.items()):
                    continue
            key = ",".join(
                f"{n}={v}" for n, v in zip(fam.labelnames, lv)
            )
            value = float(child.value)
            if rule.rate:
                pkey = (rule.name, fam.name, lv)
                prev = self._prev_counts.get(pkey)
                self._prev_counts[pkey] = (value, now)
                if prev is None or now <= prev[1]:
                    continue  # first sighting: no rate yet
                out[key] = max(value - prev[0], 0.0) / (now - prev[1])
            else:
                out[key] = value
        return out

    def _signal_values(
        self, rule: AlertRule, now: float, slo_snap: dict | None
    ) -> dict[str, float]:
        """(instance key -> current value) for one rule; an empty dict
        means the signal has nothing to say (no series yet, no SLO
        tracker), which reads as condition-false."""
        sel = rule.selector
        if sel.startswith("metric:"):
            return self._metric_values(rule, now)
        if sel.startswith("slo."):
            if not slo_snap:
                return {}
            if sel == "slo.max_burn_rate":
                return {
                    "": max(
                        slo_snap.get("error_burn_rate", 0.0),
                        slo_snap.get("latency_burn_rate", 0.0),
                    )
                }
            field_name = sel[len("slo."):]
            v = slo_snap.get(field_name)
            return {"": float(v)} if isinstance(v, (int, float)) else {}
        if sel == "breaker.state":
            from predictionio_tpu.resilience.breaker import breaker_states

            return {
                name: _BREAKER_LEVELS.get(snap.get("state"), 0.0)
                for name, snap in breaker_states().items()
            }
        if sel == "capacity.headroom_frac":
            from predictionio_tpu.obs.capacity import capacity_snapshot

            v = capacity_snapshot(self.app, self.registry).get(
                "headroom_frac"
            )
            return {"": float(v)} if isinstance(v, (int, float)) else {}
        if sel.startswith("costs."):
            # per-app signals from the cost ledger: each app keys its own
            # alert instance, so "cost_skew" names WHICH tenant is noisy
            ledger = getattr(self.app, "costs", None)
            if ledger is None:
                return {}
            try:
                return {
                    f"app={a}": float(v)
                    for a, v in ledger.signal(sel[len("costs."):]).items()
                }
            except Exception:
                log.exception(
                    "alert rule %s: cost signal %s failed", rule.name, sel
                )
                return {}
        log.warning("alert rule %s: unknown selector %s", rule.name, sel)
        return {}

    # -- the state machine ---------------------------------------------------

    def _emit(self, event: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(event)
        for sink in self.sinks:
            try:
                sink(event)
            except Exception:
                log.exception("alert sink failed")
        if event.get("event") == FIRING and self.incidents is not None:
            try:
                self.incidents.record(event, app=self.app)
            except Exception:
                log.exception("incident recording failed")

    def _transition(
        self,
        rule: AlertRule,
        key: str,
        to: str,
        value: float | None,
        now_wall: float,
        loud: bool = True,
    ) -> None:
        """Count + record one transition.  ``loud`` transitions (firing,
        and resolving FROM firing) go to every sink; quiet ones (pending,
        and a pending that clears without ever firing) stay in the event
        ring + debug log — webhook noise for a blip that never paged is
        exactly the alarm fatigue for_s exists to prevent."""
        self._m_transitions.labels(rule.name, to).inc()
        event = {
            "event": to if to != OK else "resolved",
            "rule": rule.name,
            "key": key,
            "value": value,
            "threshold": rule.threshold,
            "direction": rule.direction,
            "severity": rule.severity,
            "description": rule.description,
            "at": round(now_wall, 3),
        }
        if loud and to == FIRING or (loud and event["event"] == "resolved"):
            self._emit(event)
        else:
            with self._lock:
                self._events.append(event)
            log.info(
                "alert %s: %s %s value=%s",
                event["event"], rule.name, key, value,
            )

    def _freeze_rule(self, rule: AlertRule, tick_n: int) -> None:
        """Mark a rule's instances seen-this-tick without evaluating them:
        a transient signal-read failure keeps every state exactly where it
        was (no spurious resolves, no re-fires, no duplicate bundles)."""
        with self._lock:
            for (rname, _key), inst in self._instances.items():
                if rname == rule.name:
                    inst.seen_tick = tick_n

    def tick(self) -> dict[str, int]:
        """One evaluation pass; returns {state: count} over all instances.
        Never raises — the watch loop must outlive any one bad signal."""
        t0 = time.perf_counter()
        now = self._clock()
        now_wall = time.time()
        q = getattr(self.app, "quality", None) if self.app is not None else None
        if q is not None:
            try:
                # freshen pio_drift_state{...} so the metric selector reads
                # current detector states, not the last scrape's
                q.refresh_gauges()
            except Exception:
                pass
        slo = getattr(self.app, "slo", None) if self.app is not None else None
        slo_snap = None
        slo_failed = False
        if slo is not None:
            try:
                slo_snap = slo.snapshot()
            except Exception:
                # a tracker that EXISTS but failed to read is a transient,
                # not a missing signal: its rules must freeze, not resolve
                slo_failed = True
        firing_per_rule: dict[str, int] = {}
        counts = {OK: 0, PENDING: 0, FIRING: 0}
        with self._lock:
            self._ticks += 1
            tick_n = self._ticks
        for rule in self.rules:
            if slo_failed and rule.selector.startswith("slo."):
                self._freeze_rule(rule, tick_n)
                continue
            try:
                values = self._signal_values(rule, now, slo_snap)
            except Exception:
                # a transient read failure must FREEZE the rule's
                # instances for this tick — treating it as "signal
                # vanished" would loudly resolve a firing alert only to
                # re-fire (and re-bundle) it next tick
                log.exception("alert rule %s evaluation failed", rule.name)
                self._freeze_rule(rule, tick_n)
                continue
            for key, value in values.items():
                ikey = (rule.name, key)
                with self._lock:
                    inst = self._instances.get(ikey)
                    if inst is None:
                        inst = self._instances[ikey] = _Instance()
                inst.seen_tick = tick_n
                inst.value = value
                breached = rule.breached(value)
                if inst.state == OK:
                    if breached:
                        inst.state = PENDING
                        inst.since = now
                        self._transition(
                            rule, key, PENDING, value, now_wall, loud=False
                        )
                if inst.state == PENDING:
                    if not breached:
                        inst.state = OK
                        inst.since = None
                        self._transition(
                            rule, key, OK, value, now_wall, loud=False
                        )
                    elif now - (inst.since or now) >= rule.for_s:
                        inst.state = FIRING
                        inst.fired_at = now_wall
                        self._transition(rule, key, FIRING, value, now_wall)
                elif inst.state == FIRING and rule.cleared(value):
                    inst.state = OK
                    inst.since = None
                    inst.fired_at = None
                    self._transition(rule, key, OK, value, now_wall)
            # instances whose signal vanished (breaker registry reset, a
            # series gone): a firing alert with no evidence left resolves,
            # and the instance record is DELETED — parking it would grow
            # the table without bound under label churn (a fleet's
            # replica:<host:port> breakers over weeks of autoscaling)
            with self._lock:
                stale = [
                    (k, i)
                    for k, i in self._instances.items()
                    if k[0] == rule.name and i.seen_tick != tick_n
                ]
            for (rname, key), inst in stale:
                if inst.state == FIRING:
                    self._transition(rule, key, OK, None, now_wall)
                with self._lock:
                    self._instances.pop((rname, key), None)
        # rate bookkeeping for series not seen this tick ages out with
        # them (tick-thread-only state, like the writes in
        # _metric_values; a pruned live series costs one first-sighting
        # skip on recovery)
        self._prev_counts = {
            k: v for k, v in self._prev_counts.items() if v[1] == now
        }
        with self._lock:
            for (rname, _key), inst in self._instances.items():
                counts[inst.state] = counts.get(inst.state, 0) + 1
                if inst.state == FIRING:
                    firing_per_rule[rname] = firing_per_rule.get(rname, 0) + 1
        for rule in self.rules:
            self._m_firing.labels(rule.name).set(
                firing_per_rule.get(rule.name, 0)
            )
        dt = time.perf_counter() - t0
        self._m_eval.observe(dt)
        with self._lock:
            self._tick_seconds += dt
            self._last_tick_wall = now_wall
        return counts

    # -- synthetic events ----------------------------------------------------

    def note_event(
        self,
        name: str,
        message: str,
        severity: str = "info",
        key: str = "",
        **detail: Any,
    ) -> None:
        """Record an out-of-band event as a synthetic already-resolved
        alert (the autoscaler's scale actions use this): it lands in the
        event ring, the transitions counter, and every sink, so incident
        timelines explain capacity changes — but it never fires, never
        snapshots an incident, and holds no instance state."""
        self._m_transitions.labels(name, "resolved").inc()
        event = {
            "event": "resolved",
            "synthetic": True,
            "rule": name,
            "key": key,
            "severity": severity,
            "description": message,
            "at": round(time.time(), 3),
            **detail,
        }
        with self._lock:
            self._events.append(event)
        for sink in self.sinks:
            try:
                sink(event)
            except Exception:
                log.exception("alert sink failed")

    # -- exposition ----------------------------------------------------------

    def firing(self) -> list[dict[str, Any]]:
        return [a for a in self.active() if a["state"] == FIRING]

    def active(self) -> list[dict[str, Any]]:
        """Every non-ok instance, firing first, oldest first within state."""
        by_rule = {r.name: r for r in self.rules}
        now = self._clock()
        rows: list[dict[str, Any]] = []
        with self._lock:
            items = list(self._instances.items())
        for (rname, key), inst in items:
            if inst.state == OK:
                continue
            rule = by_rule.get(rname)
            rows.append(
                {
                    "rule": rname,
                    "key": key,
                    "state": inst.state,
                    "severity": rule.severity if rule else "warning",
                    "value": inst.value,
                    "threshold": rule.threshold if rule else None,
                    "for_s": rule.for_s if rule else None,
                    "age_s": round(
                        max(now - inst.since, 0.0), 3
                    ) if inst.since is not None else None,
                    "fired_at": inst.fired_at,
                    "description": rule.description if rule else "",
                }
            )
        rows.sort(
            key=lambda a: (
                0 if a["state"] == FIRING else 1,
                -(a["age_s"] or 0.0),
            )
        )
        return rows

    def recent_events(self, limit: int = 50) -> list[dict[str, Any]]:
        with self._lock:
            events = list(self._events)
        return events[::-1][: max(limit, 0)]

    def snapshot(self) -> dict[str, Any]:
        """The ``/alerts.json`` body."""
        active = self.active()
        with self._lock:
            ticks = self._ticks
            tick_seconds = self._tick_seconds
            last = self._last_tick_wall
        return {
            "alerts": active,
            "firing": sum(1 for a in active if a["state"] == FIRING),
            "pending": sum(1 for a in active if a["state"] == PENDING),
            "recent": self.recent_events(),
            "rules": [r.to_dict() for r in self.rules],
            "ticks": ticks,
            "eval_seconds_total": round(tick_seconds, 6),
            "interval_s": self.interval_s,
            "last_tick_at": last,
            "running": self._thread is not None,
        }

    # -- the loop ------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="pio-alert-evaluator", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            thread = self._thread
            self._thread = None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5.0)

    def _run(self) -> None:
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                self.tick()
            except Exception:
                log.exception("alert evaluator tick failed")
            self._wake.wait(self.interval_s)
            self._wake.clear()


def render_alerts_text(snap: Mapping[str, Any]) -> str:
    """Human one-screen rendering of an /alerts.json body (pio alerts)."""
    lines = [
        f"alerts: {snap.get('firing', 0)} firing, "
        f"{snap.get('pending', 0)} pending "
        f"({len(snap.get('rules', []))} rules, "
        f"{snap.get('ticks', 0)} ticks)"
    ]
    for a in snap.get("alerts", []):
        age = a.get("age_s")
        lines.append(
            f"  [{a.get('state', '?').upper():>7}] {a.get('rule')}"
            + (f"{{{a['key']}}}" if a.get("key") else "")
            + f" value={a.get('value')} threshold={a.get('threshold')}"
            + (f" age={age:.0f}s" if isinstance(age, (int, float)) else "")
            + f" severity={a.get('severity')}"
        )
    recent = snap.get("recent", [])[:8]
    if recent:
        lines.append("recent transitions (newest first):")
        for e in recent:
            lines.append(
                f"  {e.get('event'):>8} {e.get('rule')}"
                + (f"{{{e['key']}}}" if e.get("key") else "")
                + (" [synthetic]" if e.get("synthetic") else "")
            )
    # a federated body (fleet/federation.py) rides per-replica rows along
    for rid, info in sorted((snap.get("replicas") or {}).items()):
        if info is None:
            lines.append(f"replica {rid}: (no alerts scrape)")
        else:
            lines.append(
                f"replica {rid}: {info.get('firing', 0)} firing, "
                f"{info.get('pending', 0)} pending"
            )
    for err in snap.get("source_errors", []):
        lines.append(f"source error: {err}")
    return "\n".join(lines)
