"""Cross-process distributed tracing: context propagation + span fragments.

PR 3 gave every request a correlation id and PR 6/8 split device time into
stages and shards — but a request that crosses aio → prediction server →
storage daemon still yields per-process span trees stitched only by grepping
a request id.  This module is the propagation half of the fix:

- W3C-traceparent-style headers, ``X-Pio-Trace-Id`` (one id for the whole
  cross-process request) and ``X-Pio-Parent-Span`` (the caller's span id, so
  a callee's root span parents correctly instead of orphaning);
- per-span identity: every :class:`~predictionio_tpu.obs.tracing.Span`
  mints a span id and records a wall-clock start, so finished span trees
  flatten into *fragments* — flat parent-linked records a collector can
  merge across processes;
- a bounded per-process :class:`FragmentStore` served at
  ``GET /spans.json?trace_id=`` (obs/http.py), which is what the assembler
  (``obs/timeline.py`` / ``pio trace``) fetches and clock-aligns.

Propagation rides the existing contextvar machinery: the HTTP front ends
adopt the incoming headers (:func:`adopt_trace_context` +
:func:`bind_parent_span`), ``RemoteClient`` forwards
:func:`propagation_headers` on every outbound storage call next to the
request id it already forwards, and the MicroBatcher re-binds the first
wave member's context around ``batch_fn`` so a wave's storage calls join
that request's trace.  Everything is stdlib-only and never raises into the
caller — telemetry must not break serving.
"""

from __future__ import annotations

import contextvars
import os
import random
import secrets
import threading
import time
from collections import OrderedDict
from typing import Any, Mapping

from predictionio_tpu.obs.contention import ContendedLock
from predictionio_tpu.obs.logging import get_request_id, get_trace_id

#: headers under which trace context travels (request and response)
TRACE_ID_HEADER = "X-Pio-Trace-Id"
PARENT_SPAN_HEADER = "X-Pio-Parent-Span"

#: hostile-header bound: ids longer than this are truncated/dropped so one
#: crafted request cannot bloat every fragment it touches
_ID_MAX = 64

#: span-id generator: seeded once from the OS, then pure userspace.
#: secrets.token_hex would cost an os.urandom syscall PER SPAN on the
#: serving hot path — and a syscall releases the GIL mid-submission, which
#: measurably breaks MicroBatcher wave coalescing under concurrency.  Span
#: ids need per-process uniqueness, not cryptographic strength.
_rand = random.Random(secrets.randbits(64) ^ (os.getpid() << 16))


def new_span_id() -> str:
    """Mint a 16-hex span id (the W3C parent-id width)."""
    return f"{_rand.getrandbits(64):016x}"


#: the caller's span id adopted from X-Pio-Parent-Span — what this
#: process's ROOT spans parent to (None = this process starts the trace)
_parent_span_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_parent_span", default=None
)


def _header(headers: Mapping[str, str] | None, name: str) -> str:
    """Case-tolerant header lookup (email.Message, lower-cased dicts, and
    plain test dicts) — local so httpd can import this module."""
    if not headers:
        return ""
    return headers.get(name) or headers.get(name.lower()) or ""


def adopt_trace_context(
    headers: Mapping[str, str] | None, request_id: str
) -> tuple[str, str | None]:
    """The front-end half of propagation: ``(trace_id, parent_span_id)``
    from the incoming headers.  A request without a trace header starts a
    new trace under its request id (so trace id == request id for edge
    requests, and every request is traceable without opt-in)."""
    tid = _header(headers, TRACE_ID_HEADER).strip() or request_id
    if len(tid) > _ID_MAX:
        tid = tid[:_ID_MAX]
    parent = _header(headers, PARENT_SPAN_HEADER).strip() or None
    if parent and len(parent) > _ID_MAX:
        parent = None
    return tid, parent


def bind_parent_span(parent: str | None) -> contextvars.Token:
    return _parent_span_var.set(parent)


def reset_parent_span(token: contextvars.Token) -> None:
    _parent_span_var.reset(token)


def get_parent_span() -> str | None:
    return _parent_span_var.get()


def current_trace_context() -> tuple[str | None, str | None]:
    """(trace_id, span-id-to-parent-under) of the current context: the
    innermost open span when there is one, else the adopted parent.  What
    the MicroBatcher captures at submit so the wave worker can re-bind it."""
    tid = get_trace_id()
    if tid is None:
        return None, None
    from predictionio_tpu.obs.tracing import current_span

    sp = current_span()
    sid = getattr(sp, "span_id", None) or _parent_span_var.get()
    return tid, sid


def propagation_headers() -> dict[str, str]:
    """The outbound headers a cross-process client forwards: the bound
    trace id plus the innermost open span's id as the parent — so the
    callee's spans parent under the call site, not under nothing."""
    tid, sid = current_trace_context()
    if tid is None:
        return {}
    headers = {TRACE_ID_HEADER: tid}
    if sid:
        headers[PARENT_SPAN_HEADER] = sid
    return headers


# ---------------------------------------------------------------------------
# process identity

_process_name: str | None = None
_process_lock = threading.Lock()


def set_process_name(name: str, overwrite: bool = False) -> None:
    """Name this process's fragments (first server wins: a `pio deploy`
    with an embedded event server stays "predictionserver")."""
    global _process_name
    with _process_lock:
        if _process_name is None or overwrite:
            _process_name = name


def process_label() -> str:
    """``name:pid`` — what distinguishes fragment sets in the assembler."""
    return f"{_process_name or 'pio'}:{os.getpid()}"


# ---------------------------------------------------------------------------
# fragment store


class FragmentStore:
    """Bounded per-process store of finished span fragments, by trace id.

    LRU over traces (newest-touched kept) with a per-trace span cap, so a
    hot serving process holds the last ~``max_traces`` requests' fragments
    in constant memory.  ``snapshot(trace_id=...)`` is the
    ``GET /spans.json`` body the cross-process assembler fetches.
    """

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        # every finished traced root span collects here; metered so a
        # /spans.json scrape stalling the serving path is attributable
        self._lock = ContendedLock("fragment_store")
        self._traces: OrderedDict[str, list[dict[str, Any]]] = OrderedDict()

    def add(self, trace_id: str, fragment: dict[str, Any]) -> None:
        self.add_many(trace_id, (fragment,))

    def add_many(
        self, trace_id: str, fragments: Any
    ) -> None:
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
            else:
                self._traces.move_to_end(trace_id)
            for f in fragments:
                if len(spans) >= self.max_spans_per_trace:
                    break
                spans.append(f)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)

    def fragments(self, trace_id: str) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def trace_ids(self) -> list[str]:
        """Known trace ids, newest-touched first."""
        with self._lock:
            return list(reversed(self._traces))

    def snapshot(
        self, trace_id: str | None = None, limit: int = 50
    ) -> dict[str, Any]:
        """The ``/spans.json`` body: process identity + wall clock (the
        assembler's coarse alignment hint) + either one trace's fragments
        or a listing of known trace ids."""
        body: dict[str, Any] = {
            "process": process_label(),
            "pid": os.getpid(),
            "now": round(time.time(), 6),
        }
        if trace_id is not None:
            body["trace_id"] = trace_id
            body["spans"] = self.fragments(trace_id)
        else:
            with self._lock:
                ids = list(reversed(self._traces))
                body["traces"] = {
                    tid: len(self._traces[tid])
                    for tid in ids[: max(limit, 0)]
                }
        return body

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


#: process-default store (tests may hold their own for isolation)
FRAGMENTS = FragmentStore()


def collect(root_span: Any, store: FragmentStore | None = None) -> None:
    """Flatten one finished ROOT span tree into fragments.

    Called by ``tracing.trace.__exit__`` for roots that carry a trace id;
    children parent to their tree parent's span id, the root to the
    cross-process parent adopted from ``X-Pio-Parent-Span``."""
    tid = getattr(root_span, "trace_id", None)
    if not tid:
        return
    proc = process_label()
    out: list[dict[str, Any]] = []
    stack: list[tuple[Any, str | None]] = [
        (root_span, getattr(root_span, "parent_id", None))
    ]
    while stack:
        s, parent = stack.pop()
        frag: dict[str, Any] = {
            "trace_id": tid,
            "span_id": s.span_id,
            "name": s.name,
            "process": proc,
            "start_ts": round(s.start_ts, 6),
            "duration_s": round(s.duration_s, 9),
        }
        if parent:
            frag["parent_id"] = parent
        if s.request_id:
            frag["request_id"] = s.request_id
        if s.tags:
            frag["tags"] = dict(s.tags)
        if s.error:
            frag["error"] = s.error
        out.append(frag)
        for c in s.children:
            stack.append((c, s.span_id))
    (store or FRAGMENTS).add_many(tid, out)


def record_fragment(
    name: str,
    start_ts: float,
    duration_s: float,
    *,
    trace_id: str | None = None,
    parent_id: str | None = None,
    span_id: str | None = None,
    track: str | None = None,
    tags: Mapping[str, Any] | None = None,
    error: str | None = None,
    store: FragmentStore | None = None,
) -> dict[str, Any] | None:
    """Record a synthetic fragment (device-stage events, training
    iterations, a test client's root) outside any span tree.  ``track``
    names the timeline lane the Perfetto export puts it on (default: the
    process's span lane).  No-op without a trace id."""
    tid = trace_id or get_trace_id()
    if not tid:
        return None
    frag: dict[str, Any] = {
        "trace_id": tid,
        "span_id": span_id or new_span_id(),
        "name": name,
        "process": process_label(),
        "start_ts": round(float(start_ts), 6),
        "duration_s": round(float(duration_s), 9),
    }
    if parent_id:
        frag["parent_id"] = parent_id
    if track:
        frag["track"] = track
    if tags:
        frag["tags"] = {k: v for k, v in tags.items() if v is not None}
    if error:
        frag["error"] = error
    (store or FRAGMENTS).add(tid, frag)
    return frag


#: the order the wave stages execute in (PR 6's 4-way device_s split) —
#: durations are measured per stage; the timeline lays them end to end
_WAVE_STAGE_ORDER = ("host_gather", "h2d", "compute", "d2h")


def note_wave_events(
    meta: Mapping[str, Any] | None,
    parent: Any = None,
    store: FragmentStore | None = None,
) -> None:
    """Turn one MicroBatcher wave's per-item meta into device-track
    fragments: the stage breakdown laid end to end from the wave's
    dispatch timestamp (stages are measured as durations; the end-to-end
    layout reflects their execution order, not sub-stage gaps) plus one
    per-shard settle event per participating device of a sharded wave.
    Called by the serving handler after the wave resolves, inside the
    request context so the fragments key to the request's trace."""
    if not meta:
        return
    t0 = meta.get("wave_t0")
    if t0 is None or get_trace_id() is None:
        return
    try:
        _emit_wave_events(meta, parent, store, t0)
    except Exception:
        pass  # telemetry must never fail the request that asked for it


def _emit_wave_events(
    meta: Mapping[str, Any],
    parent: Any,
    store: FragmentStore | None,
    t0: float,
) -> None:
    parent_id = getattr(parent, "span_id", None)
    device = str(meta.get("wave_device") or "host")
    wave_tags = {
        "wave_seq": meta.get("wave_seq"),
        "wave_size": meta.get("wave_size"),
    }
    breakdown = meta.get("device_breakdown") or {}
    cursor = float(t0)
    for stage in _WAVE_STAGE_ORDER:
        dur = float(breakdown.get(stage) or 0.0)
        if dur <= 0.0:
            continue
        record_fragment(
            f"wave.{stage}",
            cursor,
            dur,
            parent_id=parent_id,
            track=f"device:{device}",
            tags={**wave_tags, "device": device, "stage": stage},
            store=store,
        )
        cursor += dur
    other = float(breakdown.get("other") or 0.0)
    if other > 0.0 and cursor == float(t0):
        # an engine that marks no stages still gets ONE device event so
        # the timeline shows where device_s went
        record_fragment(
            "wave.device",
            cursor,
            other,
            parent_id=parent_id,
            track=f"device:{device}",
            tags={**wave_tags, "device": device},
            store=store,
        )
    shard_seconds = meta.get("wave_shard_seconds") or {}
    compute_start = float(t0) + sum(
        float(breakdown.get(s) or 0.0) for s in ("host_gather", "h2d")
    )
    for dev, secs in sorted(shard_seconds.items()):
        record_fragment(
            "wave.shard",
            compute_start,
            float(secs),
            parent_id=parent_id,
            track=f"device:{dev}",
            tags={**wave_tags, "device": dev},
            store=store,
        )
