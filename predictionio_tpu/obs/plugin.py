"""MetricsSnifferPlugin — the registry consumed through the plugin seams.

Proof that the existing ``EventServerPlugin`` / ``EngineServerPlugin`` hooks
(server/plugins.py) compose with the observability subsystem: one sniffer
class serves both seams (ingest observations and serving observations have
the same 3-arg ``process`` shape), counts what flows past it into the shared
registry, and answers its ``/plugins/<type>/<name>/...`` REST surface with a
JSON snapshot of its own counters.

Register programmatically::

    ctx = PluginContext()
    ctx.register(MetricsSnifferPlugin(kind="input"))    # event server
    ctx.register(MetricsSnifferPlugin(kind="output"))   # prediction server

or via the env seam: ``PIO_PLUGINS=predictionio_tpu.obs.plugin:input_sniffer``
(and/or ``:output_sniffer``).
"""

from __future__ import annotations

from typing import Any

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.server.plugins import INPUT_SNIFFER, OUTPUT_SNIFFER


class MetricsSnifferPlugin:
    """Counts sniffed events/predictions into the metrics registry.

    ``kind="input"`` observes event ingest (args: app_id, channel_id, event)
    and increments ``pio_sniffed_events_total{event=...}``; ``kind="output"``
    observes served predictions (args: engine_instance_id, query, prediction)
    and increments ``pio_sniffed_predictions_total{engine_instance=...}``.
    """

    def __init__(
        self, kind: str = "input", registry: MetricsRegistry | None = None
    ):
        if kind not in ("input", "output"):
            raise ValueError(f"kind must be 'input' or 'output', got {kind!r}")
        self.kind = kind
        self.plugin_type = INPUT_SNIFFER if kind == "input" else OUTPUT_SNIFFER
        self.plugin_name = f"metrics-sniffer-{kind}"
        self._registry = registry or REGISTRY
        self._seen: set[str] = set()
        if kind == "input":
            self._counter = self._registry.counter(
                "pio_sniffed_events_total",
                "Events observed by the metrics sniffer plugin",
                labelnames=("event",),
            )
        else:
            self._counter = self._registry.counter(
                "pio_sniffed_predictions_total",
                "Predictions observed by the metrics sniffer plugin",
                labelnames=("engine_instance",),
            )

    #: label-cardinality cap: event names are client-supplied; past the cap
    #: new names collapse into one overflow series
    _MAX_LABELS = 100

    def process(self, a: Any, b: Any, c: Any) -> None:
        if self.kind == "input":
            # (app_id, channel_id, event)
            label = getattr(c, "event", "?")
        else:
            # (engine_instance_id, query, prediction)
            label = str(a)
        if label not in self._seen:
            if len(self._seen) >= self._MAX_LABELS:
                label = "_other"
            else:
                self._seen.add(label)
        self._counter.labels(label).inc()

    def handle_rest(self, path: str, query: dict) -> Any:
        fam = self._counter  # a MetricFamily (labeled)
        return {
            "plugin": self.plugin_name,
            "counts": {
                ",".join(lv) or "_": child.value
                for lv, child in fam.series()
            },
        }


def input_sniffer() -> MetricsSnifferPlugin:
    """PIO_PLUGINS factory: event-ingest metrics sniffer."""
    return MetricsSnifferPlugin(kind="input")


def output_sniffer() -> MetricsSnifferPlugin:
    """PIO_PLUGINS factory: serving-output metrics sniffer."""
    return MetricsSnifferPlugin(kind="output")
