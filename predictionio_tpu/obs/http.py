"""Metrics exposition routes for any :class:`HTTPApp`.

``add_metrics_routes(app)`` wires the standard three endpoints onto a server:

  GET /metrics        Prometheus text format 0.0.4
  GET /metrics.json   the JSON shape (adds p50/p95/p99 per histogram series)
  GET /traces.json    recent finished root spans (ring buffer)

Every server (prediction :8000, event :7070, admin :7071, dashboard :9000)
calls this so one scrape config covers the fleet.  Apps constructed with an
``access_key`` gate these routes like everything else on that app.
"""

from __future__ import annotations

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.tracing import recent_traces

#: Prometheus text exposition content type.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def add_metrics_routes(app, registry: MetricsRegistry | None = None):
    """Register /metrics, /metrics.json, and /traces.json on ``app``."""
    from predictionio_tpu.server.httpd import (
        Request,
        Response,
        json_response,
    )

    reg = registry or REGISTRY

    @app.route("GET", "/metrics")
    def metrics(req: Request) -> Response:
        return Response(
            200,
            reg.render_prometheus(),
            content_type=PROMETHEUS_CONTENT_TYPE,
        )

    @app.route("GET", "/metrics\\.json")
    def metrics_json(req: Request) -> Response:
        return json_response(200, reg.render_json())

    @app.route("GET", "/traces\\.json")
    def traces_json(req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", 20))
        except ValueError:
            return json_response(400, {"message": "limit must be an integer"})
        return json_response(
            200, {"traces": recent_traces(min(max(limit, 0), 256))}
        )

    return app
