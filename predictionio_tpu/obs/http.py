"""Observability routes for any :class:`HTTPApp`.

``add_observability_routes(app)`` wires the full request-lifecycle surface
onto a server:

  GET  /metrics             Prometheus text format 0.0.4 (runtime gauges are
                            re-sampled on each scrape)
  GET  /metrics.json        the JSON shape (adds p50/p95/p99 per histogram)
  GET  /traces.json         recent finished root spans (ring buffer)
  GET  /logs.json           recent structured log records (?request_id=&
                            limit=&level=)
  GET  /debug/flight.json   flight recorder: N slowest + errored requests
  POST /debug/profile       start a jax.profiler capture (?seconds=N&dir=)
  GET  /debug/profile       capture status (running / last)
  GET  /quality.json        online model quality: per-variant metrics +
                            drift state (servers constructed with a
                            QualityMonitor)
  GET  /efficiency.json     device efficiency: achieved-vs-peak roofline per
                            jitted entry point, recompile accounting (and
                            any active recompile storm), transfer tallies
  GET  /alerts.json         the alert evaluator's live state: firing/pending
                            instances, recent transitions, the rule set
  GET  /costs.json          the per-app cost ledger: open + closed windows
                            of (app, route, variant) resource rollups
  GET  /locks.json          runtime lock-order witness: executed lock-edge
                            set + observed inversions (PIO_LOCK_WITNESS=1;
                            {"enabled": false} otherwise)
  GET  /explain.json        decision provenance: per-answer records of
                            which generation/variant answered, from which
                            cache rows and filters, with item ids + raw
                            scores (?request_id= for one; `pio explain`)
  GET  /incidents.json      recorded incident bundles (newest first)
  GET  /incidents/<id>.json one full bundle (replayable by pio trace --file)
  GET  /healthz             liveness — ALWAYS ungated (load balancers carry
                            no keys); advisory SLO status rides along
  GET  /readyz              readiness checks (model loaded, stores up, ...)
  GET  /slo.json            rolling-window SLO + burn rates

Auth: pass ``access_key`` to gate everything here except ``/healthz``; apps
with an app-level ``HTTPApp(access_key=...)`` gate these like every other
route, with ``/healthz`` registered as a public route that bypasses the
app-level key.  ``POST /debug/profile`` additionally REQUIRES some key to be
configured (route-level or app-level) — an anonymous client must never be
able to arm the profiler.

Both HTTP front ends call :func:`record_request_outcome` after each request
to feed the per-app SLO tracker and flight recorder (observability routes
themselves are excluded so scrapes and probes don't pollute the SLO window).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Mapping

from predictionio_tpu.obs.capacity import capacity_snapshot
from predictionio_tpu.obs.device import device_snapshot, shards_snapshot
from predictionio_tpu.obs.disttrace import FRAGMENTS, set_process_name
from predictionio_tpu.obs.flight import FlightRecorder, current_annotations
from predictionio_tpu.obs.logging import get_log_ring
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.profiler import (
    PROFILER,
    ProfilerBusy,
    ProfilerUnsupported,
    sample_runtime_gauges,
)
from predictionio_tpu.obs.provenance import ProvenanceStore, finalize_record
from predictionio_tpu.obs.sampling import SAMPLER
from predictionio_tpu.obs.slo import SLOTracker, run_readiness
from predictionio_tpu.obs.tracing import recent_traces

#: Prometheus text exposition content type.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: observability/probe paths excluded from SLO + flight accounting
_OBS_PATHS = frozenset(
    (
        "/metrics",
        "/metrics.json",
        "/traces.json",
        "/spans.json",
        "/logs.json",
        "/quality.json",
        "/efficiency.json",
        "/shards.json",
        "/hotpath.json",
        "/capacity.json",
        "/fleet.json",
        "/alerts.json",
        "/incidents.json",
        "/costs.json",
        "/eventstore.json",
        "/locks.json",
        "/explain.json",
        "/tenants.json",
        "/healthz",
        "/readyz",
        "/slo.json",
    )
)


def is_observability_path(path: str) -> bool:
    return (
        path in _OBS_PATHS
        or path.startswith("/debug/")
        or path.startswith("/incidents/")
    )


def record_request_outcome(app, req, resp, duration_s: float, span) -> None:
    """Feed the app's SLO tracker and flight recorder with one finished
    request.  Called by both HTTP front ends; cheap no-op for apps without
    observability routes and for the observability routes themselves."""
    if is_observability_path(req.path):
        return
    trace_id = getattr(span, "trace_id", None)
    slo: SLOTracker | None = getattr(app, "slo", None)
    if slo is not None:
        # the trace id rides along as the SLO-breach exemplar: one slow or
        # errored request links straight to its assembled trace (and the
        # request id, so incident bundles can pull the decision's
        # provenance record)
        slo.record(
            resp.status < 500,
            duration_s,
            trace_id=trace_id,
            request_id=getattr(span, "request_id", None),
        )
    # per-tenant SLO scoping: the admission gate stamped the resolved
    # tenant on the request; its OWN tracker records the outcome too, so
    # tenant A's errors burn A's budget and only A's (server-wide slo
    # above stays the whole-replica view)
    tenant = getattr(req, "tenant", None)
    if tenant is not None:
        tslo = getattr(tenant, "slo", None)
        if tslo is not None and tslo is not slo:
            tslo.record(
                resp.status < 500,
                duration_s,
                trace_id=trace_id,
                request_id=getattr(span, "request_id", None),
            )
    provenance: ProvenanceStore | None = getattr(app, "provenance", None)
    if provenance is not None:
        # assemble the answer's decision record from the capture scope the
        # front end opened; the caller's telemetry guard means a capture
        # bug can never fail the request
        finalize_record(provenance, app.name, req, resp, duration_s, span)
    flight: FlightRecorder | None = getattr(app, "flight", None)
    if flight is None:
        return
    if resp.status < 500 and not flight.would_retain(duration_s):
        return  # fast path: skip span serialization for unremarkable wins
    entry: dict[str, Any] = {
        "request_id": span.request_id,
        "server": app.name,
        "method": req.method,
        "path": req.path,
        "status": resp.status,
        "duration_s": round(duration_s, 6),
        "payload_bytes": len(req.body or b""),
        "response_bytes": len(resp.encoded()[0]),
        "span": span.to_dict(),
    }
    if trace_id:
        entry["trace_id"] = trace_id
    ann = current_annotations()
    if ann:
        entry.update(ann)
    if resp.status >= 500:
        try:
            body = resp.body
            message = (
                body.get("message") if isinstance(body, dict) else None
            )
            entry["error"] = str(message if message is not None else body)[
                :500
            ]
        except Exception:
            entry["error"] = "unrenderable error body"
    flight.record(entry)


def add_observability_routes(
    app,
    registry: MetricsRegistry | None = None,
    access_key: str | None = None,
    readiness: Mapping[str, Callable[[], bool]] | None = None,
    slo: SLOTracker | None = None,
    flight: FlightRecorder | None = None,
    debug_routes: bool = True,
    quality: Any | None = None,
    hotpath: Any | None = None,
    alerts: Any | None = None,
    incidents: Any | None = None,
    costs: Any | None = None,
    provenance: ProvenanceStore | None = None,
    tenants: Any | None = None,
):
    """The full observability surface: metrics + logs + flight + profiler +
    health.  Installs ``app.slo`` / ``app.flight`` / ``app.readiness`` so
    the HTTP front ends (and the dashboard's Health panel) can reach them.

    ``access_key`` gates every route here EXCEPT ``/healthz`` — on apps
    whose ``HTTPApp(access_key=...)`` already gates globally, ``/healthz``
    is registered public so load balancers can always probe liveness.

    ``debug_routes=False`` skips /logs.json, /debug/flight.json,
    /debug/profile, and /quality.json entirely: servers that must stay open
    to anonymous clients (the event server's ingest port) expose the scrape
    surface but not log contents, error bodies, or an anonymous profiler
    trigger.

    ``quality`` (a :class:`~predictionio_tpu.obs.quality.QualityMonitor`)
    installs ``app.quality`` and — on debug-route servers — serves its
    snapshot at ``GET /quality.json``, gated like the other debug routes.

    ``alerts`` (an :class:`~predictionio_tpu.obs.alerts.AlertEvaluator`)
    installs ``app.alerts`` and — on debug-route servers — serves its live
    state at ``GET /alerts.json``; ``incidents`` (an
    :class:`~predictionio_tpu.obs.incident.IncidentRecorder`) installs
    ``app.incidents`` with ``GET /incidents.json`` (the listing) and
    ``GET /incidents/<id>.json`` (one full bundle).  Both are debug-gated
    like the flight recorder: alert state and forensic bundles describe
    the serving program and its failures.

    ``hotpath`` (a :class:`~predictionio_tpu.obs.hotpath.HotPathTracker`)
    installs ``app.hotpath``.  Debug-route servers serve the solo-path
    stage-attribution table at ``GET /hotpath.json`` (when a tracker is
    installed), ``GET /capacity.json`` (the headroom model joins whatever
    of ``app.slo`` / ``app.admission`` / ``app.microbatcher`` exists), and
    ``GET /debug/stacks.json`` (the continuous host stack sampler — the
    first request arms it; stack contents describe the program, so the
    surface is debug-gated like the flight recorder).
    """
    from predictionio_tpu.server.httpd import (
        Request,
        Response,
        error_response,
        json_response,
        key_matches,
    )

    # name this process's trace fragments after its first server (a `pio
    # deploy` with an embedded event server stays "predictionserver")
    set_process_name(app.name)
    reg = registry or REGISTRY
    app.slo = slo or SLOTracker()
    # no flight recorder without its route: the event server's ingest path
    # must not pay per-request entry construction for records nothing serves
    app.flight = (flight or FlightRecorder()) if debug_routes else None
    # decision provenance, same contract: the ring exists exactly when its
    # /explain.json surface does
    app.provenance = (
        (provenance or ProvenanceStore()) if debug_routes else None
    )
    app.readiness = dict(readiness or {})
    if quality is not None:
        app.quality = quality
    if hotpath is not None:
        app.hotpath = hotpath
    if alerts is not None:
        app.alerts = alerts
    if incidents is not None:
        app.incidents = incidents
    if costs is not None:
        app.costs = costs
    if tenants is not None:
        app.tenants = tenants
    ring = get_log_ring()

    original_route = app.route

    if access_key is not None:

        def route(method: str, pattern: str, public: bool = False):
            """Wrap handlers with the route-level key check (Bearer header
            or ?accessKey=), leaving public routes open."""
            def deco(fn):
                if public:
                    return original_route(method, pattern, public=True)(fn)

                def guarded(req: Request) -> Response:
                    if not key_matches(req, access_key):
                        return error_response(401, "Invalid accessKey.")
                    return fn(req)

                return original_route(method, pattern)(guarded)

            return deco

    else:
        route = original_route

    # -- metrics + traces (gated when a key is configured) -------------------
    def _prescrape() -> None:
        """Freshen scrape-time state: JAX runtime gauges, online-quality
        gauges (rate-limited — a feedback outage must show up as decaying
        values, not frozen ones), THEN the sparkline ring so it samples the
        refreshed numbers."""
        sample_runtime_gauges(reg)
        q = getattr(app, "quality", None)
        if q is not None:
            q.refresh_gauges()
        reg.history.sample(reg)

    @route("GET", "/metrics")
    def metrics(req: Request) -> Response:
        _prescrape()
        return Response(
            200,
            reg.render_prometheus(),
            content_type=PROMETHEUS_CONTENT_TYPE,
        )

    @route("GET", "/metrics\\.json")
    def metrics_json(req: Request) -> Response:
        _prescrape()
        return json_response(200, reg.render_json())

    @route("GET", "/traces\\.json")
    def traces_json(req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", 20))
        except ValueError:
            return json_response(400, {"message": "limit must be an integer"})
        return json_response(
            200, {"traces": recent_traces(min(max(limit, 0), 256))}
        )

    # -- cross-process span fragments ----------------------------------------
    # what the distributed-trace assembler (obs/timeline.py, `pio trace`)
    # fetches from every participating daemon; gated like /traces.json
    @route("GET", "/spans\\.json")
    def spans_json(req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", 50))
        except ValueError:
            return json_response(400, {"message": "limit must be an integer"})
        return json_response(
            200,
            FRAGMENTS.snapshot(
                trace_id=req.query.get("trace_id"),
                limit=min(max(limit, 0), 256),
            ),
        )

    # -- per-app cost ledger -------------------------------------------------
    # lives on the SCRAPE surface (not debug-gated): the same rollups are
    # already exposed as pio_cost_* series on /metrics, and the event
    # server's no-debug port must still answer `pio costs` / federation
    if costs is not None:

        @route("GET", "/costs\\.json")
        def costs_json(req: Request) -> Response:
            windows = None
            if "windows" in req.query:
                try:
                    windows = int(req.query["windows"])
                except ValueError:
                    return json_response(
                        400, {"message": "windows must be an integer"}
                    )
            return json_response(200, app.costs.snapshot(windows=windows))

    # -- tenant registry -----------------------------------------------------
    # on the SCRAPE surface like /costs.json (gated when a key is
    # configured): `pio tenants --url`, `pio status --url`, the dashboard's
    # tenant table, and federation all read this one snapshot
    if tenants is not None:

        @route("GET", "/tenants\\.json")
        def tenants_json(req: Request) -> Response:
            snap = app.tenants.snapshot()
            want = req.query.get("app")
            if want is not None:
                rows = [t for t in snap["tenants"] if t.get("app") == want]
                if not rows:
                    return json_response(
                        404, {"error": "unknown_tenant", "app": want}
                    )
                snap = dict(snap, tenants=rows)
            return json_response(200, snap)

    if not debug_routes:
        _add_health_routes(app, route)
        return app

    # -- structured log ring -------------------------------------------------
    @route("GET", "/logs\\.json")
    def logs_json(req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", 100))
        except ValueError:
            return json_response(400, {"message": "limit must be an integer"})
        records = ring.records(
            limit=min(max(limit, 0), 1024),
            request_id=req.query.get("request_id"),
            min_level=req.query.get("level"),
        )
        return Response(
            200,
            json.dumps({"logs": records}, default=str),
            content_type="application/json; charset=utf-8",
        )

    # -- online model quality ------------------------------------------------
    if quality is not None:

        @route("GET", "/quality\\.json")
        def quality_json(req: Request) -> Response:
            return json_response(200, app.quality.snapshot())

    # -- alert engine + incident recorder ------------------------------------
    # the watch loop's surfaces: live firing/pending state, and the
    # forensic bundles recorded on firing transitions.  Debug-gated like
    # the flight recorder — alert keys and bundles name breakers, routes,
    # and error bodies.
    if alerts is not None:

        @route("GET", "/alerts\\.json")
        def alerts_json(req: Request) -> Response:
            return json_response(200, app.alerts.snapshot())

    if incidents is not None:

        @route("GET", "/incidents\\.json")
        def incidents_json(req: Request) -> Response:
            return json_response(200, app.incidents.snapshot())

        @route("GET", "/incidents/(?P<iid>[^/]+)\\.json")
        def incident_bundle(req: Request) -> Response:
            path = app.incidents.get_path(req.params["iid"])
            if path is None:
                return json_response(
                    404, {"message": f"no incident {req.params['iid']!r}"}
                )
            try:
                with open(path, "r", encoding="utf-8") as f:
                    body = f.read()
            except OSError as e:
                return json_response(
                    404, {"message": f"bundle unreadable: {e}"}
                )
            return Response(
                200, body, content_type="application/json; charset=utf-8"
            )

    # -- device efficiency ---------------------------------------------------
    # debug-gated like the flight recorder: per-fn cost tables and storm
    # state describe the serving program, not the request — the event
    # server's anonymous ingest port must not leak them
    @route("GET", "/efficiency\\.json")
    def efficiency_json(req: Request) -> Response:
        return json_response(200, device_snapshot())

    # -- runtime lock-order witness ------------------------------------------
    # the executed lock-edge set + any order inversions seen by the
    # LockWitness (PIO_LOCK_WITNESS=1); debug-gated like the flight
    # recorder — held-lock stacks describe the serving program's internals
    @route("GET", "/locks\\.json")
    def locks_json(req: Request) -> Response:
        from predictionio_tpu.obs.contention import witness_snapshot

        return json_response(200, witness_snapshot())

    # -- sharded-mesh straggler scoreboard -----------------------------------
    # per-device placement attribution + the rolling straggler board: the
    # one scrape answering "which device is dragging the mesh"
    @route("GET", "/shards\\.json")
    def shards_json(req: Request) -> Response:
        return json_response(200, shards_snapshot(reg))

    # -- solo-path host-stage attribution ------------------------------------
    if hotpath is not None:

        @route("GET", "/hotpath\\.json")
        def hotpath_json(req: Request) -> Response:
            return json_response(200, app.hotpath.snapshot())

    # -- capacity / headroom model -------------------------------------------
    # the autoscaling input: observed load vs the device + admission
    # ceilings, joined with SLO burn (obs/capacity.py)
    @route("GET", "/capacity\\.json")
    def capacity_json(req: Request) -> Response:
        return json_response(200, capacity_snapshot(app, reg))

    # -- continuous host stack sampler ---------------------------------------
    # always-available host profiling: the first request arms the process
    # sampler; subsequent requests read the running aggregation.
    # ``?reset=1`` clears the aggregation first (keeps sampling) so a
    # bounded capture (`pio profile --stacks --seconds N`) reads a fresh
    # N-second window instead of everything since the sampler was armed.
    # Debug-gated like the flight recorder — stack contents describe the
    # program.
    @route("GET", "/debug/stacks\\.json")
    def stacks_json(req: Request) -> Response:
        SAMPLER.start()
        if req.query.get("reset") in ("1", "true"):
            SAMPLER.reset()
        fmt = req.query.get("format", "json")
        if fmt == "speedscope":
            return json_response(200, SAMPLER.speedscope())
        if fmt == "collapsed":
            return Response(
                200,
                SAMPLER.collapsed(),
                content_type="text/plain; charset=utf-8",
            )
        if fmt != "json":
            return json_response(
                400, {"message": "format must be json|collapsed|speedscope"}
            )
        body = SAMPLER.snapshot()
        body["collapsed"] = SAMPLER.collapsed()
        return json_response(200, body)

    # -- decision provenance -------------------------------------------------
    # per-answer decision records (generation, variant, cache, filters,
    # items + raw scores) — debug-gated like the flight recorder: records
    # name entities, payloads, and what they were answered
    @route("GET", "/explain\\.json")
    def explain_json(req: Request) -> Response:
        rid = req.query.get("request_id")
        if rid:
            rec = app.provenance.get(rid)
            if rec is None:
                return json_response(
                    404,
                    {
                        "message": f"no provenance record for request "
                        f"{rid!r} (ring capacity "
                        f"{app.provenance.capacity})"
                    },
                )
            return json_response(200, {"record": rec})
        limit = 50
        if "limit" in req.query:
            try:
                limit = int(req.query["limit"])
            except ValueError:
                return json_response(
                    400, {"message": "limit must be an integer"}
                )
        return json_response(
            200, app.provenance.snapshot(limit=min(max(limit, 0), 256))
        )

    # -- flight recorder -----------------------------------------------------
    @route("GET", "/debug/flight\\.json")
    def flight_json(req: Request) -> Response:
        limit = None
        if "limit" in req.query:
            try:
                limit = int(req.query["limit"])
            except ValueError:
                return json_response(
                    400, {"message": "limit must be an integer"}
                )
        snap = app.flight.snapshot(
            request_id=req.query.get("request_id"),
            trace_id=req.query.get("trace_id"),
            limit=limit,
        )
        return Response(
            200,
            json.dumps(snap, default=str),
            content_type="application/json; charset=utf-8",
        )

    # -- on-demand profiler --------------------------------------------------
    # arming a capture is privileged even on otherwise-open servers: without
    # ANY configured key (route-level or app-level), repeated anonymous
    # 300 s captures are a disk-fill + overhead DoS on the serving port
    profile_protected = access_key is not None or app.access_key is not None

    @route("POST", "/debug/profile")
    def profile_start(req: Request) -> Response:
        if not profile_protected:
            return json_response(
                403,
                {
                    "message": "profiling requires an access key; start the "
                    "server with an access key (--accesskey / --access-key "
                    "/ PIO_OBS_ACCESS_KEY) to enable /debug/profile"
                },
            )
        try:
            seconds = float(req.query.get("seconds", 5))
        except ValueError:
            return json_response(400, {"message": "seconds must be a number"})
        try:
            started = PROFILER.start(seconds, req.query.get("dir"))
        except ValueError as e:
            return json_response(400, {"message": str(e)})
        except ProfilerBusy as e:
            return json_response(409, {"message": str(e)})
        except ProfilerUnsupported as e:
            # 501: the verb is understood, the backend can't do it (CPU
            # wheels without profiler support, missing tensorboard plugin)
            return json_response(501, {"message": str(e)})
        return json_response(202, started)

    @route("GET", "/debug/profile")
    def profile_status(req: Request) -> Response:
        return json_response(200, PROFILER.status())

    _add_health_routes(app, route)
    return app


def _add_health_routes(app, route) -> None:
    """/healthz (public), /readyz, /slo.json — shared by both the full and
    the no-debug-routes variants of the observability surface."""
    from predictionio_tpu.server.httpd import Request, Response, json_response

    @route("GET", "/healthz", public=True)
    def healthz(req: Request) -> Response:
        return json_response(200, app.slo.healthz())

    @route("GET", "/readyz")
    def readyz(req: Request) -> Response:
        ready, results = run_readiness(app.readiness)
        return json_response(
            200 if ready else 503, {"ready": ready, "checks": results}
        )

    @route("GET", "/slo\\.json")
    def slo_json(req: Request) -> Response:
        from predictionio_tpu.resilience.breaker import breaker_states

        snap = app.slo.snapshot()
        breakers = breaker_states()
        if breakers:
            # circuit-breaker states ride the SLO surface: one scrape tells
            # the operator both "are we meeting objectives" and "which
            # dependency is being routed around"
            snap["breakers"] = breakers
        return json_response(200, snap)
