"""Online model-quality observability: prediction logging, feedback joins,
and drift detection.

The infrastructure half of observability (metrics, spans, flight recorder,
SLO) can say *p99 moved* and *which request moved it* — this module answers
whether the **model** is still any good, online, without waiting for the
offline ``pio eval`` loop:

- :class:`PredictionLog` — a bounded, O(1)-append ring the prediction
  server feeds per request/wave with ``(request_id, engine variant,
  query-feature summary, prediction summary: top-k ids + scores,
  timestamp)``; safe under heavy traffic because memory is capped and the
  hot-path cost is a few dict writes under one lock.
- :class:`QualityMonitor` (the feedback-joiner role) — the event server
  recognizes feedback events (configurable names) and joins them back to
  logged predictions on the ``X-Pio-Request-Id`` echoed by clients (or the
  ``prId`` API field, or entity id within a join window), producing rolling
  **online metrics per engine variant** — CTR, hit rate, precision@k,
  rating MAE — computed through the same :mod:`predictionio_tpu.core.metric`
  reducers the offline evaluator uses, so online and offline numbers are
  comparable.
- :class:`DriftDetector` — rolling reference-vs-current windows over
  query-feature and prediction-score distributions using fixed-bin
  :class:`HistogramSketch` histograms compared with PSI and KS statistics,
  exported as ``pio_drift_*`` gauges and an alert state machine
  (ok → warning → drifting) with hysteresis + patience so the state cannot
  flap on a single noisy window (and never flaps per scrape — evaluation
  happens only when a window completes).

Surfaces: ``GET /quality.json`` (obs/http.py, gated like the other debug
routes), the dashboard's Model-quality panel, and ``pio quality [--url]``.
Everything is stdlib-only and never touches a device.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from typing import Any, Mapping

from predictionio_tpu.core.metric import OptionAverageMetric
from predictionio_tpu.obs.contention import ContendedLock
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("predictionio_tpu.quality")

#: drift alert states (gauge values for ``pio_drift_state``)
OK, WARNING, DRIFTING = 0, 1, 2
STATE_NAMES = ("ok", "warning", "drifting")

#: PSI thresholds (industry convention: <0.1 stable, 0.1–0.25 shifting,
#: >0.25 drifted) and KS-statistic thresholds for binned distributions
PSI_WARN, PSI_DRIFT = 0.10, 0.25
KS_WARN, KS_DRIFT = 0.15, 0.30

#: hysteresis: leaving an elevated state requires the statistic to fall
#: below ``enter_threshold * EXIT_RATIO``, so values straddling a threshold
#: cannot flap the state every window
EXIT_RATIO = 0.8

#: event names treated as feedback when not configured explicitly
DEFAULT_FEEDBACK_EVENTS = ("rate", "buy", "click", "like", "view", "conversion")

#: query payload fields probed (in order) for the joinable entity id
DEFAULT_ENTITY_FIELDS = ("user", "userId", "user_id", "entityId")

#: cap on numeric query features sketched per request (cardinality guard)
_MAX_QUERY_FEATURES = 8

#: minimum seconds between per-variant online-metric gauge recomputations:
#: recomputing on EVERY feedback event would scan the whole join window
#: (metrics_window records x all reducers) under the monitor lock the
#: serving hot path contends on — at high ingest rates that stalls
#: observe_prediction (and, under the asyncio front end, the event loop)
_GAUGE_INTERVAL_S = 1.0


def _now() -> float:
    """Wall clock for record/join timestamps — module-level so tests can
    freeze it."""
    return time.time()


# ---------------------------------------------------------------------------
# histogram sketch + divergence statistics
# ---------------------------------------------------------------------------


class HistogramSketch:
    """Fixed-bin histogram over ``[lo, hi)`` with underflow/overflow slots.

    ``update`` is O(1) — one multiply and one list increment, no bisect —
    which is what lets the serving hot path sketch every query feature and
    prediction score.  Two sketches with identical bounds compare bin-wise
    (:func:`psi_statistic` / :func:`ks_statistic`); out-of-range values land
    in the under/overflow slots, which is exactly what catches a covariate
    shift that leaves the reference range entirely.
    """

    __slots__ = ("lo", "hi", "n_bins", "_inv_width", "counts", "total")

    def __init__(self, lo: float, hi: float, n_bins: int = 32):
        if not hi > lo:
            raise ValueError(f"sketch range must be non-empty: [{lo}, {hi})")
        if n_bins < 1:
            raise ValueError("n_bins must be >= 1")
        self.lo = float(lo)
        self.hi = float(hi)
        self.n_bins = n_bins
        self._inv_width = n_bins / (self.hi - self.lo)
        #: counts[0] = underflow, counts[1..n_bins] = bins, counts[-1] = overflow
        self.counts = [0] * (n_bins + 2)
        self.total = 0

    def update(self, value: float) -> None:
        if value < self.lo:
            idx = 0
        elif value >= self.hi:
            idx = self.n_bins + 1
        else:
            # min() guards the float-rounding edge where (value - lo) *
            # inv_width lands exactly on n_bins despite value < hi
            idx = 1 + min(int((value - self.lo) * self._inv_width), self.n_bins - 1)
        self.counts[idx] += 1
        self.total += 1

    def probabilities(self, alpha: float = 0.0) -> list[float]:
        """Bin probabilities; ``alpha`` applies Laplace (add-alpha)
        smoothing, which bounds the log-ratio an empty bin can contribute
        to PSI — an epsilon floor instead lets one unlucky empty bin
        contribute ~``p*ln(p/eps)`` and makes small windows false-alert."""
        t = self.total + alpha * len(self.counts)
        if t <= 0:
            t = 1.0
        return [(c + alpha) / t for c in self.counts]


def psi_statistic(
    ref: HistogramSketch, cur: HistogramSketch, alpha: float = 0.5
) -> float:
    """Population Stability Index between two same-bounds sketches:
    ``sum((q_i - p_i) * ln(q_i / p_i))`` over Laplace-smoothed bin
    probabilities.  With the default 10 bins and 256-observation windows,
    sampling noise on identical distributions stays under ~0.1 (the warning
    threshold) at the 99th percentile, while a 1.5-sigma mean shift scores
    ~2 — a 20x separation."""
    total = 0.0
    for p, q in zip(ref.probabilities(alpha), cur.probabilities(alpha)):
        total += (q - p) * math.log(q / p)
    return total


def ks_statistic(ref: HistogramSketch, cur: HistogramSketch) -> float:
    """Kolmogorov–Smirnov statistic over the binned CDFs: the maximum
    absolute CDF gap, in [0, 1]."""
    cp = cq = 0.0
    d = 0.0
    for p, q in zip(ref.probabilities(), cur.probabilities()):
        cp += p
        cq += q
        gap = abs(cp - cq)
        if gap > d:
            d = gap
    return d


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------


class DriftDetector:
    """Reference-vs-current drift watch for ONE distribution.

    The first ``window`` observations seed the frozen **reference** sketch
    (bin bounds derived from their min/max with 25% headroom so legitimate
    wobble stays in-range).  Every subsequent observation feeds the
    **current** sketch; when it holds ``window`` observations it is compared
    to the reference (PSI + KS), the alert state machine steps, and the
    current sketch resets — so evaluation happens once per completed window,
    never per scrape.

    State machine: ok → warning → drifting.  A state change requires the
    classified level to persist for ``patience`` consecutive windows, and
    leaving an elevated state additionally requires the statistic to drop
    below ``threshold * EXIT_RATIO`` (hysteresis) — one noisy window can
    never flip the state, and a value straddling a threshold cannot flap it.

    Not thread-safe on its own; :class:`QualityMonitor` serializes access.
    """

    __slots__ = (
        "name", "window", "n_bins", "psi_warn", "psi_drift", "ks_warn",
        "ks_drift", "patience", "psi_floor", "ks_floor", "state", "windows",
        "transitions", "last_psi", "last_ks", "reference", "current",
        "_seed", "_pending_level", "_pending_count",
    )

    def __init__(
        self,
        name: str,
        window: int = 256,
        n_bins: int = 10,
        psi_warn: float = PSI_WARN,
        psi_drift: float = PSI_DRIFT,
        ks_warn: float = KS_WARN,
        ks_drift: float = KS_DRIFT,
        patience: int = 2,
    ):
        if window < 2:
            raise ValueError("window must be >= 2")
        self.name = name
        self.window = window
        self.n_bins = n_bins
        self.psi_warn, self.psi_drift = psi_warn, psi_drift
        self.ks_warn, self.ks_drift = ks_warn, ks_drift
        self.patience = max(patience, 1)
        # Sampling-noise floors, added to every threshold: ~99th percentile
        # of PSI/KS between two SAME-distribution windows of this size
        # (PSI noise is chi-square-like, ~2.5(K-1)/N over K-1 bin degrees of
        # freedom; KS noise ~sqrt(2/N), damped by binning).  Without the
        # floor a small window false-alerts on multinomial noise alone; a
        # real shift scores an order of magnitude above the floor, so
        # sensitivity survives.  The failure mode for very small windows is
        # the right one: not enough data -> no alert.
        self.psi_floor = 2.5 * (n_bins + 1) / window
        self.ks_floor = 1.1 * math.sqrt(2.0 / window)
        self.state = OK
        self.windows = 0          # completed comparison windows
        self.transitions = 0      # state changes since creation
        self.last_psi = 0.0
        self.last_ks = 0.0
        self.reference: HistogramSketch | None = None
        self.current: HistogramSketch | None = None
        self._seed: list[float] | None = []
        self._pending_level: int | None = None
        self._pending_count = 0

    def update(self, value: float) -> dict[str, Any] | None:
        """Feed one observation; returns the evaluation dict when this
        observation completed a comparison window, else None."""
        value = float(value)
        if not math.isfinite(value):
            # json.loads accepts NaN/Infinity literals, so one hostile query
            # could otherwise poison the seed window (NaN min/max -> sketch
            # construction raises forever, the seed list grows per request)
            # or crash the binning arithmetic post-reference
            return None
        if self.reference is None:
            self._seed.append(value)
            if len(self._seed) < self.window:
                return None
            lo, hi = min(self._seed), max(self._seed)
            pad = (hi - lo) * 0.25 or max(abs(lo), 1.0) * 0.25
            self.reference = HistogramSketch(lo - pad, hi + pad, self.n_bins)
            for v in self._seed:
                self.reference.update(v)
            self.current = HistogramSketch(lo - pad, hi + pad, self.n_bins)
            self._seed = None
            return None
        self.current.update(value)
        if self.current.total < self.window:
            return None
        return self._evaluate()

    def _level(self, psi_v: float, ks_v: float, ratio: float = 1.0) -> int:
        if (
            psi_v >= (self.psi_drift + self.psi_floor) * ratio
            or ks_v >= (self.ks_drift + self.ks_floor) * ratio
        ):
            return DRIFTING
        if (
            psi_v >= (self.psi_warn + self.psi_floor) * ratio
            or ks_v >= (self.ks_warn + self.ks_floor) * ratio
        ):
            return WARNING
        return OK

    def classify(self, psi_v: float, ks_v: float) -> int:
        """The level this window argues for, hysteresis applied: moving DOWN
        from the present state requires clearing the EXIT_RATIO band too."""
        raw = self._level(psi_v, ks_v)
        if raw < self.state and self._level(psi_v, ks_v, EXIT_RATIO) >= self.state:
            return self.state
        return raw

    def _evaluate(self) -> dict[str, Any]:
        psi_v = psi_statistic(self.reference, self.current)
        ks_v = ks_statistic(self.reference, self.current)
        self.windows += 1
        self.last_psi, self.last_ks = psi_v, ks_v
        level = self.classify(psi_v, ks_v)
        changed: tuple[int, int] | None = None
        if level == self.state:
            self._pending_level, self._pending_count = None, 0
        else:
            if level == self._pending_level:
                self._pending_count += 1
            else:
                self._pending_level, self._pending_count = level, 1
            if self._pending_count >= self.patience:
                changed = (self.state, level)
                self.state = level
                self.transitions += 1
                self._pending_level, self._pending_count = None, 0
        self.current = HistogramSketch(
            self.current.lo, self.current.hi, self.n_bins
        )
        return {
            "psi": psi_v,
            "ks": ks_v,
            "state": self.state,
            "changed": changed,
        }

    def to_dict(self) -> dict[str, Any]:
        return {
            "state": STATE_NAMES[self.state],
            "psi": round(self.last_psi, 6),
            "ks": round(self.last_ks, 6),
            "windows": self.windows,
            "transitions": self.transitions,
            "window_size": self.window,
            "ready": self.reference is not None,
            "thresholds": {
                "psi_warn": round(self.psi_warn + self.psi_floor, 6),
                "psi_drift": round(self.psi_drift + self.psi_floor, 6),
                "ks_warn": round(self.ks_warn + self.ks_floor, 6),
                "ks_drift": round(self.ks_drift + self.ks_floor, 6),
                "psi_floor": round(self.psi_floor, 6),
                "ks_floor": round(self.ks_floor, 6),
                "patience": self.patience,
                "exit_ratio": EXIT_RATIO,
            },
        }


# ---------------------------------------------------------------------------
# online metrics — the offline reducers from core.metric, fed rolling
# (query, prediction-record, actual) triples so online and offline numbers
# share calculate()/fold-data semantics
# ---------------------------------------------------------------------------


class OnlineHitRate(OptionAverageMetric):
    """Fraction of joined predictions where ANY feedback item was
    recommended in the top-k (None when the join carried no item)."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"OnlineHitRate@{self.k}"

    def calculate_one(self, q, p, a) -> float | None:
        if not a:
            return None
        top = p["top"][: self.k]
        return 1.0 if any(item in a for item in top) else 0.0


class OnlinePrecisionAtK(OptionAverageMetric):
    """Fraction of the top-k recommended items that received feedback —
    the same score/denominator convention as the offline ``PrecisionAtK``
    (``min(k, |relevant|)``), so the two are directly comparable."""

    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"OnlinePrecision@{self.k}"

    def calculate_one(self, q, p, a) -> float | None:
        if not a:
            return None
        top = p["top"][: self.k]
        return sum(1 for item in top if item in a) / min(self.k, len(a))


class OnlineRatingMAE(OptionAverageMetric):
    """Mean absolute error between the predicted score and the feedback
    rating, over joins that carry both (None otherwise).  Smaller is
    better, so ``comparison`` is inverted like an error metric."""

    def header(self) -> str:
        return "OnlineRatingMAE"

    def calculate_one(self, q, p, a) -> float | None:
        scores: Mapping[str, float] = p["scores"]
        errs = [
            abs(scores[item] - rating)
            for item, rating in a.items()
            if rating is not None and item in scores
        ]
        return sum(errs) / len(errs) if errs else None

    def comparison(self, a: float, b: float) -> int:
        return (a < b) - (a > b)


# ---------------------------------------------------------------------------
# payload summarization (hot path — keep it allocation-light)
# ---------------------------------------------------------------------------


def summarize_query(
    payload: Any, entity_fields: tuple[str, ...] = DEFAULT_ENTITY_FIELDS
) -> tuple[dict[str, float], str | None]:
    """``(numeric feature dict, joinable entity id)`` from a query payload.

    Only numeric (non-bool) top-level fields become drift features, capped
    at ``_MAX_QUERY_FEATURES`` in sorted-key order so the tracked
    distribution set is bounded and deterministic.
    """
    features: dict[str, float] = {}
    entity: str | None = None
    # plain dict check, not typing.Mapping: JSON parsing always hands us
    # dicts, and typing's __instancecheck__ costs microseconds per call on
    # a path with a 50 µs/request budget
    if isinstance(payload, dict):
        for key in sorted(payload, key=str):
            v = payload[key]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            features[str(key)] = float(v)
            if len(features) >= _MAX_QUERY_FEATURES:
                break
        for field in entity_fields:
            v = payload.get(field)
            if v is not None:
                entity = str(v)
                break
    return features, entity


def summarize_prediction(
    rendered: Any, k: int = 10
) -> tuple[tuple[str, ...], dict[str, float], list[float]]:
    """``(top-k item ids, item -> score, score list)`` from a rendered
    prediction.  Understands the bundled engines' shapes — ranked
    ``itemScores``/``item_scores`` lists, classification ``label`` +
    ``score``/``probability`` — and degrades to an empty summary for
    anything else (quality telemetry must never fail serving)."""
    items: list[tuple[str, float]] = []
    scores: list[float] = []
    if isinstance(rendered, dict):  # see summarize_query: dict, not Mapping
        ranked = rendered.get("itemScores")
        if ranked is None:
            ranked = rendered.get("item_scores")
        if isinstance(ranked, (list, tuple)):
            for e in ranked[:k]:
                if isinstance(e, dict) and "item" in e:
                    s = e.get("score", 0.0)
                    s = float(s) if isinstance(s, (int, float)) else 0.0
                    items.append((str(e["item"]), s))
                    scores.append(s)
        else:
            for key in ("score", "probability", "prediction", "rating"):
                v = rendered.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    scores.append(float(v))
            label = rendered.get("label")
            if label is not None:
                items.append((str(label), scores[0] if scores else 0.0))
    top = tuple(item for item, _ in items)
    return top, dict(items), scores[:k]


# ---------------------------------------------------------------------------
# the monitor: prediction log + feedback joiner + drift + online metrics
# ---------------------------------------------------------------------------


class QualityMonitor:
    """One per serving process: PredictionLog ring, feedback joiner, drift
    detectors, and the online-metric gauges.

    Thread-safe: every mutation happens under one lock; the hot-path
    ``observe_prediction`` does a few dict writes plus O(1) sketch updates
    (tests bound it at 50 µs/request).  Memory is bounded everywhere — the
    ring by ``capacity``, per-variant join windows by ``metrics_window``,
    drift distributions by ``max_distributions``, sketches by their bins.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        capacity: int = 4096,
        top_k: int = 10,
        join_window_s: float = 600.0,
        metrics_window: int = 512,
        feedback_events: tuple[str, ...] | None = None,
        entity_fields: tuple[str, ...] = DEFAULT_ENTITY_FIELDS,
        drift_window: int = 256,
        drift_patience: int = 2,
        max_distributions: int = 16,
    ):
        if feedback_events is None:
            env = os.environ.get("PIO_FEEDBACK_EVENTS", "")
            feedback_events = (
                tuple(e.strip() for e in env.split(",") if e.strip())
                if env
                else DEFAULT_FEEDBACK_EVENTS
            )
        self.capacity = max(capacity, 1)
        self.top_k = top_k
        self.join_window_s = join_window_s
        self.metrics_window = metrics_window
        self.feedback_events = frozenset(feedback_events)
        self.entity_fields = tuple(entity_fields)
        self.drift_window = drift_window
        self.drift_patience = drift_patience
        self.max_distributions = max_distributions
        reg = registry or REGISTRY
        # the serving hot path (observe_prediction, per request) and the
        # ingest path (observe_feedback, per event) contend here — metered
        # so gauge-recompute stalls become pio_lock_wait_seconds mass
        self._lock = ContendedLock("quality_monitor", registry=reg)
        self._ring: deque[dict[str, Any]] = deque()
        self._by_rid: dict[str, dict[str, Any]] = {}
        self._by_entity: dict[str, dict[str, Any]] = {}
        self._variants: dict[str, dict[str, Any]] = {}
        self._detectors: dict[str, DriftDetector] = {}
        self._m_logged = reg.counter(
            "pio_quality_predictions_total",
            "Predictions logged for online quality monitoring, by variant",
            labelnames=("variant",),
        )
        self._m_joined = reg.counter(
            "pio_quality_feedback_joined_total",
            "Feedback events joined back to a logged prediction",
            labelnames=("variant", "join"),
        )
        self._m_unjoined = reg.counter(
            "pio_quality_feedback_unjoined_total",
            "Feedback events that matched no logged prediction",
        )
        self._m_online = reg.gauge(
            "pio_online_metric",
            "Rolling online quality metrics per engine variant",
            labelnames=("variant", "metric"),
        )
        self._m_psi = reg.gauge(
            "pio_drift_psi",
            "PSI of the current window vs the reference, per distribution",
            labelnames=("distribution",),
        )
        self._m_ks = reg.gauge(
            "pio_drift_ks",
            "KS statistic of the current window vs the reference",
            labelnames=("distribution",),
        )
        self._m_state = reg.gauge(
            "pio_drift_state",
            "Drift alert state per distribution: 0 ok, 1 warning, 2 drifting",
            labelnames=("distribution",),
        )
        self._m_transitions = reg.counter(
            "pio_drift_transitions_total",
            "Drift state-machine transitions, by distribution and new state",
            labelnames=("distribution", "to"),
        )
        #: online metrics via the offline reducers (core.metric)
        self.metrics = {
            "hit_rate": OnlineHitRate(k=top_k),
            "precision_at_k": OnlinePrecisionAtK(k=top_k),
            "rating_mae": OnlineRatingMAE(),
        }

    # -- prediction side (serving hot path) ----------------------------------

    def is_feedback(self, event_name: str) -> bool:
        return event_name in self.feedback_events

    def observe_prediction(
        self,
        request_id: str | None,
        query: Any,
        prediction: Any,
        variant: str = "default",
        wave_size: int | None = None,
        wave_seq: int | None = None,
        ts: float | None = None,
    ) -> None:
        """Log one served prediction.  Never raises — quality telemetry
        must not be able to fail a query."""
        try:
            self._observe_prediction(
                request_id, query, prediction, variant, wave_size, wave_seq, ts
            )
        except Exception:  # pragma: no cover - defensive
            log.debug("observe_prediction failed", exc_info=True)

    def _observe_prediction(
        self, request_id, query, prediction, variant, wave_size, wave_seq, ts
    ) -> None:
        ts = ts if ts is not None else _now()
        features, entity = summarize_query(query, self.entity_fields)
        top, scores, score_list = summarize_prediction(prediction, self.top_k)
        rec: dict[str, Any] = {
            "request_id": request_id,
            "variant": variant,
            "ts": ts,
            "entity": entity,
            "features": features,
            "top": top,
            "scores": scores,
            "actual": {},
            "joined": False,
        }
        if wave_size is not None:
            rec["wave_size"] = wave_size
        if wave_seq is not None:
            rec["wave_seq"] = wave_seq
        with self._lock:
            self._ring.append(rec)
            if request_id:
                self._by_rid[request_id] = rec
            if entity:
                self._by_entity[entity] = rec
            while len(self._ring) > self.capacity:
                old = self._ring.popleft()
                rid = old.get("request_id")
                if rid and self._by_rid.get(rid) is old:
                    del self._by_rid[rid]
                ent = old.get("entity")
                if ent and self._by_entity.get(ent) is old:
                    del self._by_entity[ent]
            vstats = self._vstats(variant)
            vstats["predictions"] += 1
            vstats["pred_ts"].append(ts)
            for name, value in features.items():
                self._drift_update(f"feature:{name}", value)
            for s in score_list:
                self._drift_update("prediction_score", s)
        self._m_logged.labels(variant).inc()

    def _vstats(self, variant: str) -> dict[str, Any]:
        vstats = self._variants.get(variant)
        if vstats is None:
            vstats = self._variants[variant] = {
                "predictions": 0,
                "feedback": 0,
                "pred_ts": deque(maxlen=max(self.capacity, 1)),
                "joined": deque(maxlen=self.metrics_window),
                "gauges_ts": 0.0,
            }
        return vstats

    def _drift_update(self, name: str, value: float) -> None:
        det = self._detectors.get(name)
        if det is None:
            if len(self._detectors) >= self.max_distributions:
                return  # cardinality guard: ignore new distributions
            det = self._detectors[name] = DriftDetector(
                name, window=self.drift_window, patience=self.drift_patience
            )
        out = det.update(value)
        if out is None:
            return
        self._m_psi.labels(name).set(out["psi"])
        self._m_ks.labels(name).set(out["ks"])
        self._m_state.labels(name).set(out["state"])
        if out["changed"] is not None:
            old, new = out["changed"]
            self._m_transitions.labels(name, STATE_NAMES[new]).inc()
            log.warning(
                "drift state changed",
                extra={
                    "distribution": name,
                    "from": STATE_NAMES[old],
                    "to": STATE_NAMES[new],
                    "psi": round(out["psi"], 6),
                    "ks": round(out["ks"], 6),
                },
            )

    # -- feedback side (event-server ingest) ---------------------------------

    def observe_feedback(
        self,
        event: Any,
        request_id: str | None = None,
        ts: float | None = None,
        app: Any = None,
    ) -> bool:
        """Join one ingested event back to a logged prediction.  Returns
        True when joined.  Never raises.  ``app`` (the ingest call's
        authenticated app id/name) is stamped on the joined record so a
        multi-tenant quality surface can attribute — and audit — which
        tenant's feedback joined which prediction."""
        try:
            return self._observe_feedback(event, request_id, ts, app)
        except Exception:  # pragma: no cover - defensive
            log.debug("observe_feedback failed", exc_info=True)
            return False

    def _observe_feedback(self, event, request_id, ts, app=None) -> bool:
        if event.event not in self.feedback_events:
            return False
        ts = ts if ts is not None else _now()
        # candidate correlation ids, most explicit first: the header id the
        # client echoed on the ingest call (the front end MINTS one when the
        # client sent none, so a miss must fall through to the next key),
        # then the event's prId API field, then a pioRequestId property
        rids = [request_id, getattr(event, "pr_id", None)]
        props = getattr(event, "properties", None)
        if props is not None and "pioRequestId" in props:
            rids.append(str(props["pioRequestId"]))
        item = event.target_entity_id
        rating = None
        if props is not None and "rating" in props:
            raw = props["rating"]
            if isinstance(raw, (int, float)) and not isinstance(raw, bool):
                rating = float(raw)
        with self._lock:
            rec = next(
                (r for rid in rids if rid and (r := self._by_rid.get(rid))),
                None,
            )
            how = "request_id"
            if rec is None and event.entity_id:
                cand = self._by_entity.get(str(event.entity_id))
                if cand is not None and ts - cand["ts"] <= self.join_window_s:
                    rec, how = cand, "entity"
            if rec is None:
                self._m_unjoined.inc()
                return False
            if item is not None:
                rec["actual"][str(item)] = rating
            if app is not None:
                rec["app"] = app
            vstats = self._vstats(rec["variant"])
            vstats["feedback"] += 1
            if not rec["joined"]:
                rec["joined"] = True
                vstats["joined"].append(rec)
            self._m_joined.labels(rec["variant"], how).inc()
            if ts - vstats["gauges_ts"] >= _GAUGE_INTERVAL_S:
                self._set_metric_gauges(rec["variant"], vstats, ts)
        return True

    # -- metrics + snapshot --------------------------------------------------

    def _compute_metrics(
        self, vstats: dict[str, Any], now: float
    ) -> dict[str, float | None]:
        """Rolling online metrics over the joins inside the window, via the
        core.metric reducers (fold-data shaped exactly like offline eval)."""
        cutoff = now - self.join_window_s
        pred_ts = vstats["pred_ts"]
        while pred_ts and pred_ts[0] < cutoff:
            pred_ts.popleft()
        recent = [rec for rec in vstats["joined"] if rec["ts"] >= cutoff]
        out: dict[str, float | None] = {
            # never None: 0 is the freshness signal that the feedback
            # pipeline stopped delivering joins (the ratio metrics below
            # keep their last value when no joins remain to score)
            "joined_in_window": float(len(recent)),
            "ctr": len(recent) / len(pred_ts) if pred_ts else None,
        }
        fold_data = [(None, [(rec["features"], rec, rec["actual"]) for rec in recent])]
        for name, metric in self.metrics.items():
            value = metric.calculate(fold_data) if recent else float("nan")
            out[name] = None if math.isnan(value) else value
        return out

    def _set_metric_gauges(
        self, variant: str, vstats: dict[str, Any], now: float
    ) -> dict[str, float | None]:
        """Recompute + export the variant's online metrics, at most once per
        ``_GAUGE_INTERVAL_S`` (except when forced by snapshot()) — the scan
        over the join window is O(metrics_window) and runs under the lock."""
        metrics = self._compute_metrics(vstats, now)
        vstats["gauges_ts"] = now
        for name, value in metrics.items():
            if value is not None:
                self._m_online.labels(variant, name).set(value)
        return metrics

    def refresh_gauges(self) -> None:
        """Rate-limited recomputation of every variant's online-metric
        gauges — called on each /metrics scrape, so the gauges keep moving
        when feedback STOPS arriving (a decaying CTR and a zero
        joined_in_window are exactly what a feedback-pipeline outage looks
        like; without this the gauges freeze at their last joined value)."""
        now = _now()
        with self._lock:
            for variant, vstats in self._variants.items():
                if now - vstats["gauges_ts"] >= _GAUGE_INTERVAL_S:
                    self._set_metric_gauges(variant, vstats, now)

    def variant_metrics(self, variant: str) -> dict[str, float | None] | None:
        """Force-computed rolling metrics for ONE variant (None when the
        variant has logged nothing) — the lifecycle controller's read."""
        now = _now()
        with self._lock:
            vstats = self._variants.get(variant)
            if vstats is None:
                return None
            return self._compute_metrics(vstats, now)

    def compare_variants(
        self, live: str, canary: str, metric: str = "hit_rate"
    ) -> dict[str, Any]:
        """Canary-vs-live comparison on one online metric — what gates a
        canary promotion: the values, and the joined-sample counts that
        say how much evidence backs them."""
        now = _now()
        with self._lock:
            out: dict[str, Any] = {"metric": metric}
            for label, key in ((live, "live"), (canary, "canary")):
                vstats = self._variants.get(label)
                if vstats is None:
                    out[f"{key}_value"] = None
                    out[f"{key}_joined"] = 0
                    continue
                metrics = self._compute_metrics(vstats, now)
                out[f"{key}_value"] = metrics.get(metric)
                out[f"{key}_joined"] = int(
                    metrics.get("joined_in_window") or 0
                )
        return out

    def record_for(self, request_id: str) -> dict[str, Any] | None:
        """Copy of the logged prediction record for one request id (swap-
        atomicity tests assert the logged variant matches the answer)."""
        with self._lock:
            rec = self._by_rid.get(request_id)
            return dict(rec) if rec is not None else None

    def drift_state(self) -> str:
        """Worst alert state across every tracked distribution."""
        with self._lock:
            worst = max(
                (det.state for det in self._detectors.values()), default=OK
            )
        return STATE_NAMES[worst]

    def snapshot(self) -> dict[str, Any]:
        """The /quality.json body: per-variant online metrics + drift."""
        now = _now()
        with self._lock:
            variants = {}
            for variant, vstats in sorted(self._variants.items()):
                # snapshot is the forced refresh path: /quality.json (and a
                # following /metrics scrape) always see current numbers
                metrics = self._set_metric_gauges(variant, vstats, now)
                variants[variant] = {
                    "predictions": vstats["predictions"],
                    "feedback_events": vstats["feedback"],
                    "joined": len(vstats["joined"]),
                    "metrics": metrics,
                }
            worst = max(
                (det.state for det in self._detectors.values()), default=OK
            )
            drift = {
                "state": STATE_NAMES[worst],
                "distributions": {
                    name: det.to_dict()
                    for name, det in sorted(self._detectors.items())
                },
            }
            log_info = {"size": len(self._ring), "capacity": self.capacity}
        return {
            "variants": variants,
            "drift": drift,
            "log": log_info,
            "join_window_s": self.join_window_s,
            "feedback_events": sorted(self.feedback_events),
            "top_k": self.top_k,
        }


#: alias documenting the ring role the monitor plays for the serving path
PredictionLog = QualityMonitor


_default_lock = threading.Lock()
_default_monitor: QualityMonitor | None = None


def default_quality() -> QualityMonitor:
    """The process-default monitor (bound to the global REGISTRY) — what the
    prediction and event servers share in the single-VM deployment so the
    feedback loop closes in-process."""
    global _default_monitor
    with _default_lock:
        if _default_monitor is None:
            _default_monitor = QualityMonitor()
        return _default_monitor


def render_quality_text(snapshot: Mapping[str, Any]) -> str:
    """Human one-screen rendering of a /quality.json snapshot (pio quality)."""
    lines = [f"drift: {snapshot.get('drift', {}).get('state', 'ok')}"]
    for name, d in snapshot.get("drift", {}).get("distributions", {}).items():
        lines.append(
            f"  {name}: state={d['state']} psi={d['psi']} ks={d['ks']} "
            f"windows={d['windows']} transitions={d['transitions']}"
        )
    for variant, v in snapshot.get("variants", {}).items():
        metrics = " ".join(
            f"{k}={v2:.4f}" if isinstance(v2, float) else f"{k}=n/a"
            for k, v2 in v.get("metrics", {}).items()
        )
        lines.append(
            f"variant {variant}: predictions={v['predictions']} "
            f"joined={v['joined']} feedback={v['feedback_events']} {metrics}"
        )
    log_info = snapshot.get("log", {})
    lines.append(
        f"log: {log_info.get('size', 0)}/{log_info.get('capacity', 0)} "
        f"records, join window {snapshot.get('join_window_s', 0)}s"
    )
    return "\n".join(lines)
