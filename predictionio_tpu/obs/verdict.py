"""The SLO verdict engine: did the fleet survive the scripted day?

Joins three evidence sources the production-day harness collects —

1. the traffic generator's own outcome log (one record per request:
   status, latency, replica/instance/variant headers, request id);
2. scraped fleet telemetry: router-side registry snapshots taken at
   every phase boundary, so per-phase quantiles come from histogram
   bucket *deltas* (:func:`~predictionio_tpu.obs.metrics.subtract_snapshots`),
   never from a second histogram family;
3. the run's incident-bundle directory.

— into a machine-readable verdict: a list of clauses, each with
``passed`` and an ``evidence`` payload (metric family, bundle path, or
exemplar request id), plus a per-phase table.  The clause catalog:

- ``phase_p99_bounded`` — every phase with a ``p99_ms`` bound holds it,
  computed from ``pio_router_forward_seconds`` bucket deltas between the
  phase's boundary snapshots;
- ``exactly_once`` — every scheduled request has exactly one outcome and
  an HTTP answer (no transport losses, no duplicate request ids); reads
  must be 2xx; writes may shed 503 only when a storage stall was
  actually injected;
- ``flip_coherence`` — every answered read names a known
  ``X-Pio-Engine-Instance`` + a variant, and once the deploy flip
  completes, only the new generation answers;
- ``autoscaler_converged`` — the live replica count ends within
  ``tolerance`` of the capacity model's recommendation;
- ``fault_reconciliation`` — EXACTLY one incident bundle per injected
  fault, naming its rule; missing, duplicate, or spurious bundles fail
  the run;
- ``tenant_isolation`` (multi-tenant days only) — every flooded tenant
  is actually shed (quota engaged), every innocent neighbor holds its
  availability/p99, and zero answers cross a tenant boundary.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping

from predictionio_tpu.obs.metrics import quantile_from_buckets, subtract_snapshots

__all__ = ["evaluate_day", "render_verdict", "LATENCY_FAMILY"]

#: the router-side request-latency family per-phase p99s are cut from
LATENCY_FAMILY = "pio_router_forward_seconds"


def _phase_delta(
    snapshots: list[Mapping[str, Any]], i: int
) -> dict[str, Any] | None:
    if i + 1 >= len(snapshots):
        return None
    return subtract_snapshots(snapshots[i + 1], snapshots[i])


def _family_quantile(
    delta: Mapping[str, Any] | None, family: str, q: float
) -> tuple[float | None, int]:
    """Aggregate a histogram family's series (e.g. per-replica) by
    elementwise bucket sum, then cut the quantile; (value_s, count)."""
    if not delta:
        return None, 0
    fam = delta.get(family)
    if not isinstance(fam, Mapping) or fam.get("type") != "histogram":
        return None, 0
    bounds = list(fam.get("bounds", []))
    agg: list[int] = []
    total = 0
    for s in fam.get("series", ()):
        buckets = list(s.get("buckets", []))
        if len(buckets) > len(agg):
            agg += [0] * (len(buckets) - len(agg))
        for j, b in enumerate(buckets):
            agg[j] += b
        total += int(s.get("count", 0))
    if total == 0:
        return None, 0
    return quantile_from_buckets(bounds, agg, total, q), total


def _counter_total(delta: Mapping[str, Any] | None, family: str) -> float:
    if not delta:
        return 0.0
    fam = delta.get(family)
    if not isinstance(fam, Mapping) or fam.get("type") != "counter":
        return 0.0
    return float(sum(s.get("value", 0.0) for s in fam.get("series", ())))


def _list_bundles(incident_dir: str | None) -> list[dict[str, Any]]:
    """Every readable bundle in the run's incident directory, with its
    path attached (the evidence pointer the verdict carries)."""
    if not incident_dir or not os.path.isdir(incident_dir):
        return []
    out = []
    for name in sorted(os.listdir(incident_dir)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(incident_dir, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict):
            doc["_path"] = path
            out.append(doc)
    return out


def _pct(lats: list[float], q: float) -> float | None:
    if not lats:
        return None
    lats = sorted(lats)
    return lats[min(int(len(lats) * q), len(lats) - 1)]


def evaluate_day(evidence: Mapping[str, Any]) -> dict[str, Any]:
    """Evidence (all keys optional unless noted):

    - ``phases`` (required): ``[{name, index, start_s, duration_s, qps,
      read_frac, p99_ms, scheduled}]``;
    - ``outcomes`` (required): the generator's outcome log;
    - ``snapshots``: ``len(phases)+1`` registry ``render_json()`` dumps,
      one per phase boundary (router-side);
    - ``costs``: ``len(phases)+1`` per-boundary device-second totals
      (float, summed over replicas);
    - ``injected``: ``[{kind, at_s, rule}]`` — ``rule`` None means the
      injection must stay bundle-silent (a clean deploy);
    - ``incident_dir``: the run's bundle directory;
    - ``incidents_after``: wall-clock stamp; only bundles recorded at or
      after it count (stale bundles from an earlier run are spurious
      evidence, not this run's);
    - ``autoscaler``: ``{desired, actual, tolerance}``;
    - ``instances``: ``{known: [...], new, flip_completed_s}`` (offsets
      in day seconds);
    - ``stall_windows``: ``[[start_s, end_s], ...]`` write-shed amnesty
      windows (storage stalls actually injected);
    - ``tenants``: ``{rows: [{app, scheduled, answered, ok, quota_shed,
      leaked, availability, p99_ms, p99_bound_ms?}], flooded: [...],
      availability_floor}`` — presence enables the ``tenant_isolation``
      clause.
    """
    phases = list(evidence.get("phases", []))
    outcomes = list(evidence.get("outcomes", []))
    snapshots = list(evidence.get("snapshots", []))
    costs = list(evidence.get("costs", []))
    clauses: list[dict[str, Any]] = []

    by_phase: dict[int, list[dict]] = {}
    for o in outcomes:
        by_phase.setdefault(int(o.get("phase_index", -1)), []).append(o)

    # -- per-phase table (generator view + telemetry view + cost view) ------
    table = []
    for i, p in enumerate(phases):
        rows = by_phase.get(i, [])
        reads = [o for o in rows if o.get("kind") == "read"]
        writes = [o for o in rows if o.get("kind") == "write"]
        read_lat = [o["latency_ms"] for o in reads if o.get("status")]
        delta = _phase_delta(snapshots, i)
        tele_p99_s, tele_n = _family_quantile(delta, LATENCY_FAMILY, 0.99)
        tele_p50_s, _ = _family_quantile(delta, LATENCY_FAMILY, 0.50)
        forwards = _counter_total(delta, "pio_router_forwards_total")
        retries = _counter_total(delta, "pio_router_retry_elsewhere_total")
        shed = _counter_total(delta, "pio_shed_total")
        device_s = None
        if i + 1 < len(costs):
            device_s = round(max(costs[i + 1] - costs[i], 0.0), 6)
        table.append(
            {
                "name": p.get("name", f"phase{i}"),
                "qps": p.get("qps"),
                "read_frac": p.get("read_frac"),
                "scheduled": p.get("scheduled", len(rows)),
                "answered": sum(1 for o in rows if o.get("status") is not None),
                "errors": sum(
                    1
                    for o in rows
                    if o.get("status") is None or int(o.get("status") or 0) >= 400
                ),
                "p50_ms": round(_pct(read_lat, 0.50), 3) if read_lat else None,
                "p99_ms": round(_pct(read_lat, 0.99), 3) if read_lat else None,
                "telemetry_p50_ms": (
                    round(tele_p50_s * 1000, 3) if tele_p50_s is not None else None
                ),
                "telemetry_p99_ms": (
                    round(tele_p99_s * 1000, 3) if tele_p99_s is not None else None
                ),
                "telemetry_requests": tele_n,
                "shed": shed,
                "retry_elsewhere_rate": round(
                    retries / forwards, 6
                ) if forwards else 0.0,
                "device_s": device_s,
                "p99_bound_ms": p.get("p99_ms"),
            }
        )

    # -- clause: phase_p99_bounded ------------------------------------------
    violations = []
    checked = 0
    for i, p in enumerate(phases):
        bound = p.get("p99_ms")
        if bound is None:
            continue
        checked += 1
        row = table[i]
        # telemetry (bucket-delta) p99 is authoritative; the generator's
        # own log is the cross-check when no snapshot pair exists
        got = row["telemetry_p99_ms"]
        source = f"metric:{LATENCY_FAMILY} bucket delta"
        if got is None:
            got = row["p99_ms"]
            source = "outcome log (no boundary snapshots)"
        if got is None:
            violations.append(
                {"phase": row["name"], "bound_ms": bound, "p99_ms": None,
                 "source": "no latency evidence"}
            )
        elif got > bound:
            violations.append(
                {"phase": row["name"], "bound_ms": bound, "p99_ms": got,
                 "source": source}
            )
    clauses.append(
        {
            "clause": "phase_p99_bounded",
            "passed": not violations,
            "detail": (
                f"{checked} bounded phase(s), {len(violations)} violation(s)"
            ),
            "evidence": {
                "metric": LATENCY_FAMILY,
                "phases": [
                    {
                        "phase": t["name"],
                        "p99_ms": t["telemetry_p99_ms"],
                        "bound_ms": t["p99_bound_ms"],
                    }
                    for t in table
                ],
                "violations": violations,
            },
        }
    )

    # -- clause: exactly_once ------------------------------------------------
    scheduled_total = sum(int(p.get("scheduled", 0)) for p in phases)
    ids_seen: dict[str, int] = {}
    for o in outcomes:
        ids_seen[o["id"]] = ids_seen.get(o["id"], 0) + 1
    duplicates = [rid for rid, n in ids_seen.items() if n > 1]
    unanswered = [o["id"] for o in outcomes if o.get("status") is None]
    missing = scheduled_total - len(ids_seen)
    stall_windows = [tuple(w) for w in evidence.get("stall_windows", [])]

    def in_stall(o: dict) -> bool:
        t = float(o.get("start_s", -1.0))
        # generous tail: a write launched inside the window may be
        # answered (shed) after the stall lifts
        return any(w0 - 1.0 <= t <= w1 + 5.0 for w0, w1 in stall_windows)

    # a 503 stamped reason=tenant_quota from a tenant the scenario
    # deliberately flooded is the admission contract WORKING, not a lost
    # read — same spirit as the storage-stall write amnesty above
    flooded_apps = set((evidence.get("tenants") or {}).get("flooded", []))

    def excused_quota_shed(o: dict) -> bool:
        return (
            int(o["status"]) == 503
            and o.get("shed_reason") == "tenant_quota"
            and o.get("app") in flooded_apps
        )

    read_failures = [
        o["id"]
        for o in outcomes
        if o.get("kind") == "read"
        and o.get("status") is not None
        and not 200 <= int(o["status"]) < 300
        and not excused_quota_shed(o)
    ]
    write_failures = [
        o["id"]
        for o in outcomes
        if o.get("kind") == "write"
        and o.get("status") is not None
        and not 200 <= int(o["status"]) < 300
        and not (int(o["status"]) == 503 and in_stall(o))
    ]
    problems = {
        "missing_outcomes": missing,
        "duplicate_ids": duplicates[:5],
        "unanswered": unanswered[:5],
        "read_failures": read_failures[:5],
        "write_failures": write_failures[:5],
    }
    ok = (
        missing == 0
        and not duplicates
        and not unanswered
        and not read_failures
        and not write_failures
    )
    clauses.append(
        {
            "clause": "exactly_once",
            "passed": ok,
            "detail": (
                f"{scheduled_total} scheduled, {len(outcomes)} outcomes, "
                f"{len(unanswered)} unanswered, {len(duplicates)} duplicate "
                f"id(s), {len(read_failures)} failed read(s), "
                f"{len(write_failures)} unexcused failed write(s)"
            ),
            "evidence": problems,
        }
    )

    # -- clause: flip_coherence ---------------------------------------------
    inst_ev = evidence.get("instances") or {}
    known = set(inst_ev.get("known", []))
    new_inst = inst_ev.get("new")
    flip_done = inst_ev.get("flip_completed_s")
    incoherent = []
    stale_after_flip = []
    if known:
        for o in outcomes:
            if o.get("kind") != "read" or o.get("status") != 200:
                continue
            inst = o.get("instance")
            if inst not in known or not o.get("variant"):
                incoherent.append(o["id"])
            elif (
                flip_done is not None
                and new_inst is not None
                and float(o.get("start_s", 0.0)) > float(flip_done)
                and inst != new_inst
            ):
                stale_after_flip.append(o["id"])
    clauses.append(
        {
            "clause": "flip_coherence",
            "passed": not incoherent and not stale_after_flip,
            "detail": (
                f"{len(known)} known instance(s); "
                f"{len(incoherent)} answer(s) outside the known set or "
                f"variant-less, {len(stale_after_flip)} old-generation "
                f"answer(s) after the flip completed"
            ),
            "evidence": {
                "known_instances": sorted(known),
                "new_instance": new_inst,
                "flip_completed_s": flip_done,
                "exemplar_incoherent": incoherent[:5],
                "exemplar_stale_after_flip": stale_after_flip[:5],
            },
        }
    )

    # -- clause: autoscaler_converged ---------------------------------------
    auto = evidence.get("autoscaler") or {}
    desired = auto.get("desired")
    actual = auto.get("actual")
    tolerance = auto.get("tolerance", 1)
    if desired is None or actual is None:
        auto_ok = False
        auto_detail = "no autoscaler evidence (desired/actual missing)"
    else:
        auto_ok = abs(int(actual) - int(desired)) <= int(tolerance)
        auto_detail = (
            f"recommended {desired} replica(s), running {actual}, "
            f"tolerance ±{tolerance}"
        )
    clauses.append(
        {
            "clause": "autoscaler_converged",
            "passed": auto_ok,
            "detail": auto_detail,
            "evidence": dict(auto, metric="pio_autoscaler_desired_replicas"),
        }
    )

    # -- clause: fault_reconciliation ---------------------------------------
    injected = list(evidence.get("injected", []))
    bundles = _list_bundles(evidence.get("incident_dir"))
    after = evidence.get("incidents_after")
    if after is not None:
        # "now" is the bundle's capture stamp; "at" the alert's firing
        # stamp — either proves the bundle belongs to this run
        bundles = [
            b
            for b in bundles
            if float(b.get("now") or b.get("at") or 0.0) >= float(after)
        ]
    expected: dict[str, int] = {}
    for inj in injected:
        rule = inj.get("rule")
        if rule:
            expected[rule] = expected.get(rule, 0) + 1
    got: dict[str, list[str]] = {}
    for b in bundles:
        got.setdefault(str(b.get("rule")), []).append(b["_path"])
    missing_rules = {
        r: n - len(got.get(r, [])) for r, n in expected.items()
        if len(got.get(r, [])) < n
    }
    duplicate_rules = {
        r: got[r] for r, n in expected.items() if len(got.get(r, [])) > n
    }
    spurious = {r: paths for r, paths in got.items() if r not in expected}
    recon_ok = not missing_rules and not duplicate_rules and not spurious
    clauses.append(
        {
            "clause": "fault_reconciliation",
            "passed": recon_ok,
            "detail": (
                f"{sum(expected.values())} injected fault(s) expecting a "
                f"bundle, {len(bundles)} bundle(s) found; "
                f"missing={missing_rules or 'none'} "
                f"duplicate={sorted(duplicate_rules) or 'none'} "
                f"spurious={sorted(spurious) or 'none'}"
            ),
            "evidence": {
                "incident_dir": evidence.get("incident_dir"),
                "expected_rules": expected,
                "bundles": {r: paths for r, paths in got.items()},
                "missing": missing_rules,
                "duplicate": duplicate_rules,
                "spurious": spurious,
            },
        }
    )

    # -- clause: tenant_isolation -------------------------------------------
    # only evaluated for multi-tenant days (evidence carries a "tenants"
    # block built by the tenant-day harness); single-tenant days are
    # unaffected.  Containment means three things at once: the flooded
    # tenant IS shed (quota engaged, reason=tenant_quota), every innocent
    # neighbor keeps its availability/p99, and no answer ever crosses a
    # tenant boundary (X-Pio-App / engine-instance leakage).
    ten_ev = evidence.get("tenants")
    if ten_ev is not None:
        rows = list(ten_ev.get("rows", []))
        flooded = set(ten_ev.get("flooded", []))
        floor = float(ten_ev.get("availability_floor", 0.99))
        leaks = [
            {"app": r.get("app"), "leaked": r.get("leaked")}
            for r in rows
            if int(r.get("leaked", 0) or 0)
        ]
        unshed = [
            r.get("app")
            for r in rows
            if r.get("app") in flooded and not int(r.get("quota_shed", 0) or 0)
        ]
        starved = []
        for r in rows:
            if r.get("app") in flooded:
                continue
            avail = r.get("availability")
            if avail is None or float(avail) < floor:
                starved.append({"app": r.get("app"), "availability": avail})
            bound = r.get("p99_bound_ms")
            p99 = r.get("p99_ms")
            if bound is not None and p99 is not None and p99 > bound:
                starved.append(
                    {"app": r.get("app"), "p99_ms": p99, "bound_ms": bound}
                )
        ten_ok = not leaks and not unshed and not starved
        clauses.append(
            {
                "clause": "tenant_isolation",
                "passed": ten_ok,
                "detail": (
                    f"{len(rows)} tenant(s), {len(flooded)} flooded; "
                    f"leaks={len(leaks)}, quota-not-engaged={unshed or 'none'}, "
                    f"starved-neighbors={len(starved)}"
                ),
                "evidence": {
                    "metric": "pio_tenant_shed_total",
                    "availability_floor": floor,
                    "rows": rows,
                    "leaks": leaks,
                    "flooded_without_shed": unshed,
                    "starved": starved,
                },
            }
        )

    return {
        "pass": all(c["passed"] for c in clauses),
        "scenario": evidence.get("scenario"),
        "seed": evidence.get("seed"),
        "clauses": clauses,
        "phases": table,
        "requests": {
            "scheduled": scheduled_total,
            "answered": sum(1 for o in outcomes if o.get("status") is not None),
        },
    }


def render_verdict(verdict: Mapping[str, Any]) -> str:
    """The human-readable phase table + clause lines ``pio day`` prints."""
    lines = []
    cols = (
        ("phase", 14), ("qps", 6), ("sched", 6), ("ans", 6), ("err", 5),
        ("p50ms", 8), ("p99ms", 8), ("bound", 7), ("shed", 6),
        ("retry%", 7), ("dev_s", 8),
    )
    lines.append(" ".join(f"{name:>{w}}" for name, w in cols))

    def fmt(v, w):
        if v is None:
            return " " * (w - 1) + "-"
        if isinstance(v, float):
            return f"{v:>{w}.2f}"
        return f"{v!s:>{w}}"

    for t in verdict.get("phases", []):
        p99 = t.get("telemetry_p99_ms")
        p50 = t.get("telemetry_p50_ms")
        if p99 is None:
            p99 = t.get("p99_ms")
        if p50 is None:
            p50 = t.get("p50_ms")
        row = (
            t.get("name"), t.get("qps"), t.get("scheduled"), t.get("answered"),
            t.get("errors"), p50, p99, t.get("p99_bound_ms"), t.get("shed"),
            (t.get("retry_elsewhere_rate") or 0.0) * 100, t.get("device_s"),
        )
        lines.append(" ".join(fmt(v, w) for v, (_, w) in zip(row, cols)))
    lines.append("")
    for c in verdict.get("clauses", []):
        mark = "PASS" if c["passed"] else "FAIL"
        lines.append(f"[{mark}] {c['clause']}: {c['detail']}")
        if not c["passed"]:
            lines.append(f"       evidence: {json.dumps(c['evidence'], default=str)}")
    lines.append("")
    lines.append(
        f"VERDICT: {'PASS' if verdict.get('pass') else 'FAIL'}"
    )
    return "\n".join(lines)
