"""Cross-process trace assembly: one merged host+device timeline per trace.

``obs/disttrace.py`` is the propagation half — every process accumulates
span *fragments* (flat parent-linked records) in a bounded store served at
``GET /spans.json?trace_id=``.  This module is the collection half:

- :func:`fetch_spans` pulls one process's fragment set over HTTP and
  estimates its clock offset from the request/response timestamps (the
  NTP-style midpoint estimate: the server's ``now`` is compared against the
  midpoint of the client's send/receive clock, so a daemon whose wall clock
  drifts still lands on one shared timeline to within ~RTT/2);
- :func:`assemble` merges any number of fragment sets — HTTP bodies,
  recorded files, the local in-process store — into a single
  :class:`Timeline`: spans linked across process boundaries through the
  ``X-Pio-Parent-Span`` ids the front ends adopted, device-stage and
  per-shard events from the MicroBatcher wave timeline riding as their own
  tracks, orphans (a parent that died before exporting, e.g. a SIGKILLed
  daemon) kept as extra roots rather than dropped;
- the three renders: an indented text waterfall (:meth:`Timeline.render_text`),
  plain JSON (:meth:`Timeline.to_dict`), and **Chrome trace-event JSON**
  (:meth:`Timeline.to_chrome_trace`) loadable by Perfetto / chrome://tracing
  — one ``pid`` lane per process, one ``tid`` per track (the span lane plus
  a ``device:<label>`` lane per participating device/shard).

``pio trace <id> --from URL,URL`` (tools/cli.py) is the operator entry
point; the dashboard waterfall panel renders the same Timeline as HTML.
Everything is stdlib-only and read-only: assembling a trace never touches
the serving path.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Iterable, Mapping

from predictionio_tpu.obs.disttrace import FRAGMENTS, FragmentStore

#: chrome trace-event timestamps are integer-ish microseconds
_US = 1e6


class TraceAssemblyError(Exception):
    """No usable fragments for the requested trace."""


def estimate_offset(
    server_now: float, t_sent: float, t_recv: float
) -> float:
    """Seconds to SUBTRACT from the server's wall-clock timestamps to land
    them on the collector's clock: ``server_now`` was sampled somewhere
    between the collector's ``t_sent`` and ``t_recv``, so the midpoint is
    the best single-sample estimate (error bounded by half the RTT)."""
    return float(server_now) - (float(t_sent) + float(t_recv)) / 2.0


def fetch_spans(
    url: str,
    trace_id: str,
    access_key: str | None = None,
    timeout: float = 10.0,
) -> dict[str, Any]:
    """GET ``{url}/spans.json?trace_id=`` and return the body with an
    ``_offset_s`` clock-alignment estimate and ``_source`` attached."""
    import urllib.parse
    import urllib.request

    base = url.rstrip("/")
    full = f"{base}/spans.json?trace_id={urllib.parse.quote(trace_id)}"
    headers = {"Authorization": f"Bearer {access_key}"} if access_key else {}
    req = urllib.request.Request(full, headers=headers)
    t_sent = time.time()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        raw = r.read()
    t_recv = time.time()
    body = json.loads(raw.decode("utf-8"))
    if not isinstance(body, dict):
        raise TraceAssemblyError(f"{full} returned a non-object body")
    now = body.get("now")
    body["_offset_s"] = (
        estimate_offset(now, t_sent, t_recv) if isinstance(now, (int, float))
        else 0.0
    )
    body["_source"] = base
    return body


def load_fragment_file(path: str) -> list[dict[str, Any]]:
    """Load a recorded fragment set from disk: a ``/spans.json`` body, a
    list of such bodies, or a bare fragment list (wrapped into one body).
    File-loaded sets get no clock offset — they were recorded, not live."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        bodies = [data]
    elif isinstance(data, list) and data and all(
        isinstance(d, dict) and "spans" in d for d in data
    ):
        bodies = data
    elif isinstance(data, list):
        bodies = [{"process": path, "spans": data}]
    else:
        raise TraceAssemblyError(f"{path}: not a fragment set")
    for b in bodies:
        b.setdefault("_source", path)
        b.setdefault("_offset_s", 0.0)
    return bodies


def local_spans(
    trace_id: str, store: FragmentStore | None = None
) -> dict[str, Any]:
    """This process's own fragment set, shaped like a ``/spans.json`` body
    (the collector is often also a participant: a test client's root span,
    a training run's iteration track)."""
    body = (store or FRAGMENTS).snapshot(trace_id=trace_id)
    body["_offset_s"] = 0.0
    body["_source"] = "local"
    return body


class TraceNode:
    """One assembled span with aligned timing and its children."""

    __slots__ = ("fragment", "start_s", "children", "process", "orphan")

    def __init__(self, fragment: dict[str, Any], start_s: float):
        self.fragment = fragment
        #: collector-clock wall start (offset-aligned)
        self.start_s = start_s
        self.children: list["TraceNode"] = []
        self.process = str(fragment.get("process") or "?")
        #: True when the fragment names a parent span that was never
        #: exported (its process died, or the store evicted the trace)
        self.orphan = False

    @property
    def name(self) -> str:
        return str(self.fragment.get("name") or "?")

    @property
    def duration_s(self) -> float:
        return float(self.fragment.get("duration_s") or 0.0)

    @property
    def track(self) -> str:
        return str(self.fragment.get("track") or "spans")

    def to_dict(self, t0: float) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "process": self.process,
            "start_s": round(self.start_s - t0, 6),
            "duration_s": round(self.duration_s, 9),
            "span_id": self.fragment.get("span_id"),
        }
        for key in ("parent_id", "request_id", "tags", "error", "track"):
            if self.fragment.get(key):
                d[key] = self.fragment[key]
        if self.orphan:
            d["orphan"] = True
        if self.children:
            d["children"] = [c.to_dict(t0) for c in self.children]
        return d


class Timeline:
    """One assembled cross-process trace (see module docstring)."""

    def __init__(
        self,
        trace_id: str,
        roots: list[TraceNode],
        nodes: Mapping[str, TraceNode],
        processes: list[str],
        offsets: Mapping[str, float],
        source_errors: list[str] | None = None,
    ):
        self.trace_id = trace_id
        self.roots = roots
        self.nodes = dict(nodes)
        #: participating process labels, in first-seen order
        self.processes = processes
        #: applied clock offset per source (seconds subtracted)
        self.offsets = dict(offsets)
        #: fetch/load failures the collector tolerated (dead daemons)
        self.source_errors = list(source_errors or [])

    @property
    def t0(self) -> float:
        return min((n.start_s for n in self.nodes.values()), default=0.0)

    @property
    def span_count(self) -> int:
        return len(self.nodes)

    def device_events(self) -> list[TraceNode]:
        """The device-track events (wave stages, per-shard settles,
        training iterations) inside this trace."""
        return [n for n in self.nodes.values() if n.track != "spans"]

    def to_dict(self) -> dict[str, Any]:
        t0 = self.t0
        return {
            "trace_id": self.trace_id,
            "processes": list(self.processes),
            "span_count": self.span_count,
            "clock_offsets_s": {
                k: round(v, 6) for k, v in self.offsets.items()
            },
            "source_errors": list(self.source_errors),
            "spans": [r.to_dict(t0) for r in self.roots],
        }

    # -- text render ---------------------------------------------------------

    def render_text(self) -> str:
        t0 = self.t0
        end = max(
            (n.start_s + n.duration_s for n in self.nodes.values()),
            default=t0,
        )
        lines = [
            f"trace {self.trace_id} — {len(self.processes)} process(es), "
            f"{self.span_count} span(s), {(end - t0) * 1e3:.1f} ms"
        ]
        for err in self.source_errors:
            lines.append(f"  ! {err}")

        def walk(node: TraceNode, depth: int) -> None:
            rel = (node.start_s - t0) * 1e3
            mark = "~" if node.track != "spans" else ""
            orphan = " (orphaned: parent span not exported)" if node.orphan else ""
            err = (
                f" ERROR: {node.fragment['error']}"
                if node.fragment.get("error")
                else ""
            )
            lines.append(
                f"{'  ' * depth}{mark}{node.name} [{node.process}"
                f"{'' if node.track == 'spans' else ' ' + node.track}] "
                f"+{rel:.2f}ms {node.duration_s * 1e3:.3f}ms{orphan}{err}"
            )
            for c in node.children:
                walk(c, depth + 1)

        for root in self.roots:
            walk(root, 1)
        return "\n".join(lines)

    # -- Chrome trace-event / Perfetto render --------------------------------

    def to_chrome_trace(self) -> dict[str, Any]:
        """The ``chrome://tracing`` / Perfetto JSON object format: complete
        ("X") events with microsecond timestamps relative to the trace
        start, one ``pid`` per process and one ``tid`` per track, named
        through metadata events."""
        t0 = self.t0
        pids = {p: i + 1 for i, p in enumerate(self.processes)}
        events: list[dict[str, Any]] = []
        tids: dict[tuple[str, str], int] = {}
        for proc, pid in pids.items():
            events.append(
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": proc},
                }
            )

        def tid_for(proc: str, track: str) -> int:
            key = (proc, track)
            tid = tids.get(key)
            if tid is None:
                # spans lane first (tid 1), device tracks after, per process
                tid = tids[key] = (
                    1
                    if track == "spans"
                    else 2 + sum(1 for p, _ in tids if p == proc)
                )
                events.append(
                    {
                        "ph": "M",
                        "name": "thread_name",
                        "pid": pids[proc],
                        "tid": tid,
                        "args": {"name": track},
                    }
                )
            return tid

        for node in sorted(self.nodes.values(), key=lambda n: n.start_s):
            frag = node.fragment
            args: dict[str, Any] = {}
            if frag.get("tags"):
                args.update(frag["tags"])
            for key in ("request_id", "span_id", "parent_id", "error"):
                if frag.get(key):
                    args[key] = frag[key]
            events.append(
                {
                    "ph": "X",
                    "name": node.name,
                    "cat": "device" if node.track != "spans" else "span",
                    "pid": pids[node.process],
                    "tid": tid_for(node.process, node.track),
                    "ts": round((node.start_s - t0) * _US, 3),
                    "dur": round(max(node.duration_s, 0.0) * _US, 3),
                    "args": args,
                }
            )
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id},
        }


def assemble(
    sources: Iterable[Mapping[str, Any]],
    trace_id: str,
    source_errors: list[str] | None = None,
) -> Timeline:
    """Merge fragment sets (``/spans.json``-shaped bodies) into one
    :class:`Timeline`.  Duplicate span ids (a fragment fetched twice, or the
    local store shadowing an HTTP fetch of the same process) keep the first
    copy; fragments whose parent never arrived become extra roots flagged
    ``orphan`` — a dead process must not hide its callees' spans."""
    nodes: dict[str, TraceNode] = {}
    processes: list[str] = []
    offsets: dict[str, float] = {}
    for body in sources:
        offset = float(body.get("_offset_s") or 0.0)
        source = str(body.get("_source") or body.get("process") or "?")
        offsets[source] = offset
        proc_default = body.get("process")
        for frag in body.get("spans") or ():
            if frag.get("trace_id") not in (None, trace_id):
                continue
            sid = frag.get("span_id")
            if not sid or sid in nodes:
                continue
            start = float(frag.get("start_ts") or 0.0) - offset
            frag = dict(frag)
            if proc_default and not frag.get("process"):
                # recorded bodies carry the process label once, at the top
                frag["process"] = proc_default
            node = TraceNode(frag, start)
            nodes[sid] = node
            if node.process not in processes:
                processes.append(node.process)
    if not nodes:
        raise TraceAssemblyError(
            f"no fragments found for trace {trace_id!r} "
            f"(sources: {sorted(offsets)})"
        )
    roots: list[TraceNode] = []
    for node in nodes.values():
        parent_id = node.fragment.get("parent_id")
        parent = nodes.get(parent_id) if parent_id else None
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            node.orphan = bool(parent_id)
            roots.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.start_s)
    roots.sort(key=lambda n: n.start_s)
    return Timeline(
        trace_id, roots, nodes, processes, offsets, source_errors
    )


def collect_trace(
    trace_id: str,
    urls: Iterable[str] = (),
    files: Iterable[str] = (),
    include_local: bool = False,
    store: FragmentStore | None = None,
    access_key: str | None = None,
    timeout: float = 10.0,
) -> Timeline:
    """The one-call collector: fetch every URL's ``/spans.json`` (tolerating
    dead daemons — a SIGKILLed process costs its fragments, not the whole
    assembly), load recorded files, optionally fold in this process's own
    store, and assemble.

    URL fetches run concurrently so the wait is bounded by the slowest
    single source, not the sum — a caller blocking a request thread (the
    dashboard waterfall) pays one timeout even when several daemons in
    ``urls`` are dead."""
    bodies: list[Mapping[str, Any]] = []
    errors: list[str] = []
    url_list = list(urls)
    if url_list:
        with ThreadPoolExecutor(
            max_workers=min(len(url_list), 8),
            thread_name_prefix="pio-trace-fetch",
        ) as pool:
            fetches = [
                pool.submit(
                    fetch_spans,
                    url,
                    trace_id,
                    access_key=access_key,
                    timeout=timeout,
                )
                for url in url_list
            ]
            for url, fut in zip(url_list, fetches):
                try:
                    bodies.append(fut.result())
                except Exception as e:
                    errors.append(f"{url}: {type(e).__name__}: {e}")
    for path in files:
        try:
            bodies.extend(load_fragment_file(path))
        except Exception as e:
            errors.append(f"{path}: {type(e).__name__}: {e}")
    if include_local:
        bodies.append(local_spans(trace_id, store=store))
    return assemble(bodies, trace_id, source_errors=errors)
