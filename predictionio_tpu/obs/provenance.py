"""Decision provenance: explain any answer the fleet served, then replay it.

Metrics say p99 moved, traces say where the time went, the quality log says
what was answered — none of them say *why*: which generation's bytes, which
canary hash-side, which factor-cache rows, which filters, which wave.  The
:class:`ProvenanceStore` keeps a bounded ring of per-answer
**ProvenanceRecord** dicts — engine instance + generation id + manifest
checksum, variant/role, ShardPlan axes, factor-cache hit/miss counts,
degraded fallbacks, filters applied, wave id/size/seq, the event-history
watermark consulted, and the returned item ids with raw scores — captured
on every answered request by both HTTP front ends.

Two capture levels:

- **cheap** (always on): everything replay needs — bounded dicts and
  counts, no per-item filter contents.  Budget: tens of microseconds on
  the solo path (bench section ``provenance_capture``; tier-1 bounds p50
  below 50 µs).
- **deep** (opt-in per request via the ``X-Pio-Explain: 1`` header): adds
  filter item lists, wave-mate request ids, and the post-extraction query.

Handlers and engines attach detail through :func:`note` / :func:`note_deep`
— contextvar scopes exactly like ``obs.flight.annotate``: a request scope
the front ends open, plus a wave scope ``_serve_wave`` binds on the
MicroBatcher's worker/finalizer threads (where the request scope is not
visible).  The record is assembled once, at request finish, by
:func:`finalize_record` (called from ``record_request_outcome``).

:func:`replay_request` is the proof: rebind the manifest-named,
checksum-verified generation offline, re-execute the recorded query, and
diff item ids + scores bit-exactly — any divergence names the field
(different generation, corrupt bytes, shifted item, drifted score).
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Any, Mapping

#: per-request opt-in for deep capture
EXPLAIN_HEADER = "X-Pio-Explain"

#: answers retained by the always-on ring (newest evict oldest)
RECORD_CAPACITY = 1024

#: deep-capture list fields are clipped to this many entries
DEEP_LIST_CAP = 64

#: request-scoped capture state: {"deep": bool, "notes": {}, "deep_notes": {}}
_scope_var: contextvars.ContextVar[dict[str, Any] | None] = (
    contextvars.ContextVar("pio_provenance_scope", default=None)
)

#: wave-scoped collector bound by the MicroBatcher wave (worker/finalizer
#: threads, where the request scope is invisible); takes precedence
_wave_var: contextvars.ContextVar[dict[str, Any] | None] = (
    contextvars.ContextVar("pio_provenance_wave", default=None)
)


def wants_deep(headers: Mapping[str, str] | None) -> bool:
    """Did the request opt into deep capture?  Case-tolerant header lookup
    (the threaded server hands an email.Message, aio a lower-cased dict)."""
    if not headers:
        return False
    v = headers.get(EXPLAIN_HEADER) or headers.get(EXPLAIN_HEADER.lower()) or ""
    return v in ("1", "true", "yes")


def begin_capture(deep: bool = False) -> contextvars.Token:
    """Open a fresh provenance scope for the current request."""
    return _scope_var.set({"deep": deep, "notes": {}, "deep_notes": {}})


def end_capture(token: contextvars.Token) -> None:
    _scope_var.reset(token)


def deep_active() -> bool:
    s = _scope_var.get()
    return bool(s is not None and s["deep"])


def note(**fields: Any) -> None:
    """Attach cheap (always-retained) fields to the in-flight answer's
    provenance record.  Inside a wave scope the fields collect wave-side
    and reach each member through the wave's per-item result; otherwise
    they land on the open request scope (no-op when neither is open)."""
    w = _wave_var.get()
    if w is not None:
        w.update(fields)
        return
    s = _scope_var.get()
    if s is not None:
        s["notes"].update(fields)


def note_deep(**fields: Any) -> None:
    """Attach deep-capture fields: kept only for requests that presented
    ``X-Pio-Explain``.  Wave scopes collect them unconditionally (the wave
    cannot see which members opted in); the request scope filters."""
    w = _wave_var.get()
    if w is not None:
        w.setdefault("_deep", {}).update(fields)
        return
    s = _scope_var.get()
    if s is not None and s["deep"]:
        s["deep_notes"].update(fields)


def begin_wave() -> contextvars.Token:
    """Bind a wave collector (MicroBatcher worker/finalizer threads)."""
    return _wave_var.set({})


def end_wave(token: contextvars.Token) -> dict[str, Any]:
    """Close the wave collector and return what it gathered."""
    collected = _wave_var.get() or {}
    _wave_var.reset(token)
    return collected


def clip(items: Any, cap: int = DEEP_LIST_CAP) -> list:
    """Bound a deep-capture list field (sets/tuples accepted)."""
    return list(items)[:cap]


def item_scores(rendered: Any) -> list[dict[str, Any]] | None:
    """The (item id, raw score) pairs of a rendered prediction, or None
    when the answer has no ``itemScores`` shape (marker/test engines)."""
    if not isinstance(rendered, dict):
        return None
    scores = rendered.get("itemScores")
    if not isinstance(scores, list):
        return None
    return [
        {"item": d.get("item"), "score": d.get("score")}
        for d in scores
        if isinstance(d, dict)
    ]


def note_answer(rendered: Any) -> None:
    """Record what was returned: ``items`` (ids + raw scores) for
    itemScores-shaped answers; the whole rendered body otherwise (those
    engines' answers are small — the ring stays bounded either way)."""
    items = item_scores(rendered)
    if items is not None:
        note(items=items)
    else:
        note(answer=rendered)


# -- generation identity (memoized manifest reads) ---------------------------

#: (manifest key, instance id) -> generation info; checksums are immutable
#: per instance id, so one manifest read per generation per process
_GEN_MEMO: dict[tuple[str, str], dict[str, Any]] = {}
_GEN_MEMO_CAP = 128
_gen_memo_lock = threading.Lock()


def generation_info(gen_store: Any, instance_id: str) -> dict[str, Any] | None:
    """The manifest's identity of one generation: checksum, status, shard
    axes, and the engine coordinates replay needs to rebuild the store.
    Memoized; None when the engine has no generation store."""
    if gen_store is None or instance_id is None:
        return None
    memo_key = (
        f"{gen_store.engine_id}/{gen_store.engine_version}/"
        f"{gen_store.engine_variant}",
        instance_id,
    )
    with _gen_memo_lock:
        hit = _GEN_MEMO.get(memo_key)
    if hit is not None:
        return hit
    try:
        gen = gen_store.get(instance_id)
    except Exception:
        return None
    if gen is None:
        return None
    from predictionio_tpu.lifecycle.generations import shard_axes

    info = {
        "instance": instance_id,
        "checksum": gen.checksum,
        "status": gen.status,
        "shard_axes": shard_axes(gen.shard_plan),
        "engine": {
            "id": gen_store.engine_id,
            "version": gen_store.engine_version,
            "variant": gen_store.engine_variant,
        },
    }
    with _gen_memo_lock:
        if len(_GEN_MEMO) >= _GEN_MEMO_CAP:
            _GEN_MEMO.clear()
        _GEN_MEMO[memo_key] = info
    return info


def binding_fields(deployed: Any, binding: Any) -> dict[str, Any]:
    """The cheap per-answer binding identity: which generation, which
    hash-side, and (memoized) what the manifest says about its bytes."""
    fields: dict[str, Any] = {
        "instance_id": binding.instance.id,
        "variant": deployed.binding_label(binding),
        "role": binding.role,
    }
    factory = getattr(binding.instance, "engine_factory", None)
    if factory:
        fields["engine_factory"] = factory
    gen = generation_info(deployed.generation_store, binding.instance.id)
    if gen is not None:
        fields["generation"] = gen
    return fields


def note_binding(deployed: Any, binding: Any) -> None:
    note(**binding_fields(deployed, binding))


# -- the bounded record store ------------------------------------------------


class ProvenanceStore:
    """Bounded ring of per-answer provenance records, indexed by request
    id.  Crash-tolerant by construction: capture never raises into the
    request path (the front ends guard the finalize call) and the ring
    evicts oldest-first, so a hot server holds the last N decisions and
    nothing more."""

    def __init__(self, capacity: int = RECORD_CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._by_rid: dict[str, dict[str, Any]] = {}
        self._total = 0

    def record(self, entry: dict[str, Any]) -> None:
        rid = entry.get("request_id")
        with self._lock:
            self._total += 1
            if len(self._ring) == self.capacity:
                evicted = self._ring[0]
                old_rid = evicted.get("request_id")
                if old_rid is not None and (
                    self._by_rid.get(old_rid) is evicted
                ):
                    del self._by_rid[old_rid]
            self._ring.append(entry)
            if rid is not None:
                self._by_rid[rid] = entry

    def get(self, request_id: str) -> dict[str, Any] | None:
        with self._lock:
            return self._by_rid.get(request_id)

    def snapshot(self, limit: int = 50) -> dict[str, Any]:
        with self._lock:
            records = list(self._ring)[-limit:][::-1]
            total = self._total
        return {
            "recorded_total": total,
            "capacity": self.capacity,
            "records": records,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_rid.clear()
            self._total = 0


def finalize_record(
    store: ProvenanceStore,
    server_name: str,
    req: Any,
    resp: Any,
    duration_s: float,
    span: Any,
) -> None:
    """Assemble + store the answer's record from the open capture scope.
    Requests where nothing noted provenance (status pages, admin verbs)
    leave no record; called from ``record_request_outcome`` under the
    front ends' telemetry guard, so a capture bug can't fail a request."""
    scope = _scope_var.get()
    if scope is None or not scope["notes"]:
        return
    entry: dict[str, Any] = {
        "request_id": getattr(span, "request_id", None),
        "trace_id": getattr(span, "trace_id", None),
        "ts": round(time.time(), 3),
        "server": server_name,
        "path": req.path,
        "status": resp.status,
        "duration_s": round(duration_s, 6),
        "capture": "deep" if scope["deep"] else "cheap",
    }
    entry.update(scope["notes"])
    if scope["deep"] and scope["deep_notes"]:
        entry["deep"] = dict(scope["deep_notes"])
    store.record(entry)


# -- offline replay ----------------------------------------------------------


class ReplayError(RuntimeError):
    """The record cannot be replayed at all (no payload, unknown engine)."""


def _diff_items(
    recorded: list[dict[str, Any]],
    replayed: list[dict[str, Any]],
    score_tolerance: float,
) -> list[dict[str, Any]]:
    """Name every divergent field between the recorded and replayed item
    lists.  Scores compare bit-exactly by default (``repr`` equality, so
    NaN == NaN and -0.0 != 0.0); ``score_tolerance`` relaxes that for
    cross-backend replays (documented caveat, not the default)."""
    divergences: list[dict[str, Any]] = []
    if len(recorded) != len(replayed):
        divergences.append(
            {
                "field": "items.length",
                "recorded": len(recorded),
                "replayed": len(replayed),
            }
        )
    for i, (a, b) in enumerate(zip(recorded, replayed)):
        if a.get("item") != b.get("item"):
            divergences.append(
                {
                    "field": f"items[{i}].item",
                    "recorded": a.get("item"),
                    "replayed": b.get("item"),
                }
            )
            continue
        sa, sb = a.get("score"), b.get("score")
        if score_tolerance > 0 and sa is not None and sb is not None:
            if abs(float(sa) - float(sb)) <= score_tolerance:
                continue
        elif repr(sa) == repr(sb):
            continue
        divergences.append(
            {
                "field": f"items[{i}].score",
                "recorded": sa,
                "replayed": sb,
            }
        )
    return divergences


def replay_request(
    record: Mapping[str, Any],
    storage: Any = None,
    score_tolerance: float = 0.0,
) -> dict[str, Any]:
    """Re-execute a recorded decision offline and diff it bit-exactly.

    Rebinds the record's manifest-named generation from the
    :class:`~predictionio_tpu.lifecycle.generations.GenerationStore`
    (checksum-verified — corrupt or swapped bytes are a named divergence,
    not a silent re-bless), re-runs the recorded query through the same
    engine factory, and compares returned item ids + raw scores.

    Returns ``{"matched": bool, "divergences": [...], "replayed_items":
    [...], "instance_id": ...}``; ``matched`` is True only when every
    field is bit-identical.  Divergences name what moved:

    - ``generation``          — instance absent from the manifest
    - ``generation.checksum`` — manifest names DIFFERENT bytes now
    - ``generation.bytes``    — stored bytes fail checksum (corrupt/torn)
    - ``items[i].item``       — a different item id at rank i
    - ``items[i].score``      — same item, drifted score (torn cache row
      or nondeterministic op)
    - ``answer``              — non-itemScores answers compare whole
    """
    from predictionio_tpu.data.storage.config import get_storage
    from predictionio_tpu.lifecycle.generations import (
        CorruptModelError,
        GenerationStore,
    )

    instance_id = record.get("instance_id")
    payload = record.get("payload")
    gen = record.get("generation") or {}
    engine_coords = gen.get("engine") or {}
    factory_name = record.get("engine_factory")
    if instance_id is None or payload is None:
        raise ReplayError(
            "record is not replayable: missing instance_id or payload "
            "(was it captured by an answered /queries.json request?)"
        )
    storage = storage or get_storage()
    divergences: list[dict[str, Any]] = []

    gen_store = GenerationStore(
        storage.models(),
        engine_coords.get("id", "default"),
        engine_coords.get("version", "default"),
        engine_coords.get("variant", "default"),
    )
    manifest_gen = gen_store.get(instance_id)
    if manifest_gen is None:
        divergences.append(
            {
                "field": "generation",
                "recorded": instance_id,
                "replayed": None,
                "detail": "instance is not in the generation manifest",
            }
        )
        return _replay_report(record, divergences, None)
    recorded_checksum = gen.get("checksum")
    if recorded_checksum and manifest_gen.checksum != recorded_checksum:
        divergences.append(
            {
                "field": "generation.checksum",
                "recorded": recorded_checksum,
                "replayed": manifest_gen.checksum,
                "detail": "manifest now names a different generation's bytes",
            }
        )
        return _replay_report(record, divergences, None)
    try:
        gen_store.verify(manifest_gen)
    except CorruptModelError as e:
        divergences.append(
            {
                "field": "generation.bytes",
                "recorded": recorded_checksum,
                "replayed": None,
                "detail": str(e),
            }
        )
        return _replay_report(record, divergences, None)

    from predictionio_tpu.core.engine import resolve_engine_factory
    from predictionio_tpu.server.prediction_server import (
        DeployedEngine,
        _render_prediction,
    )

    instance = storage.engine_instances().get(instance_id)
    if instance is None:
        raise ReplayError(
            f"engine instance {instance_id!r} is not in the instance store"
        )
    factory = resolve_engine_factory(factory_name or instance.engine_factory)
    deployed = DeployedEngine(
        factory(), instance, storage, generation_store=gen_store
    )
    query = deployed.extract_query(dict(payload))
    _, prediction = deployed.predict(query)
    rendered = _render_prediction(prediction)
    replayed = item_scores(rendered)

    recorded_items = record.get("items")
    if recorded_items is not None and replayed is not None:
        divergences.extend(
            _diff_items(recorded_items, replayed, score_tolerance)
        )
    elif record.get("answer") is not None:
        if record["answer"] != rendered:
            divergences.append(
                {
                    "field": "answer",
                    "recorded": record["answer"],
                    "replayed": rendered,
                }
            )
    else:
        raise ReplayError(
            "record holds neither items nor an answer body to diff"
        )
    return _replay_report(record, divergences, replayed or rendered)


def _replay_report(
    record: Mapping[str, Any],
    divergences: list[dict[str, Any]],
    replayed: Any,
) -> dict[str, Any]:
    return {
        "matched": not divergences,
        "request_id": record.get("request_id"),
        "instance_id": record.get("instance_id"),
        "divergences": divergences,
        "replayed": replayed,
    }
