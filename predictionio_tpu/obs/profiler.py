"""On-demand JAX profiling + runtime gauges for a live server.

``POST /debug/profile?seconds=N`` starts a ``jax.profiler`` trace capture on
a running server without restarting it — the "grab a profile of the slow
fleet member right now" workflow (DrJAX's profiling emphasis; the Spark job
UI role in the reference).  ``start_trace`` runs on the request thread (it
only arms collection, and a failure must surface as the HTTP status); the
capture *wait* and ``stop_trace`` run on a dedicated background thread so
the request thread answers immediately — a stalled profiler must never hold
an event-loop executor slot for N seconds.

:func:`sample_runtime_gauges` refreshes compile-cache / device-memory /
live-buffer gauges; the metrics exposition route calls it on each scrape so
the gauges are current without a sampler thread.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time
import weakref
from typing import Any

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

#: upper bound on one capture; profiles are for debugging, not surveillance
MAX_CAPTURE_SECONDS = 300.0


class ProfilerUnsupported(RuntimeError):
    """jax.profiler is unavailable or refused to start on this backend."""


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (jax allows one trace at a time)."""


def _start_trace(out_dir: str) -> None:
    """Indirection point (tests stub these; jax imports stay lazy)."""
    import jax

    jax.profiler.start_trace(out_dir)


def _stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfilerController:
    """One capture at a time, finished off-thread.

    ``start`` arms the trace and hands the wait+stop to a daemon thread;
    ``status`` reports the in-flight capture or the last finished one.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._running: dict[str, Any] | None = None
        self._last: dict[str, Any] | None = None
        self._wakeup = threading.Event()

    def start(self, seconds: float, out_dir: str | None = None) -> dict[str, Any]:
        if not 0 < seconds <= MAX_CAPTURE_SECONDS:
            raise ValueError(
                f"seconds must be in (0, {MAX_CAPTURE_SECONDS:g}]"
            )
        out_dir = out_dir or os.path.join(
            tempfile.gettempdir(), "pio-profile"
        )
        with self._lock:
            if self._running is not None:
                raise ProfilerBusy(
                    f"capture already running into {self._running['dir']}"
                )
            self._running = {
                "dir": out_dir,
                "seconds": seconds,
                "started": time.time(),
            }
        try:
            _start_trace(out_dir)
        except Exception as e:
            with self._lock:
                self._running = None
            raise ProfilerUnsupported(
                f"jax profiler unavailable on this backend: {e}"
            ) from e
        self._wakeup.clear()
        threading.Thread(
            target=self._finish,
            args=(seconds, out_dir),
            name="pio-profiler",
            daemon=True,
        ).start()
        return {"profiling": True, "seconds": seconds, "dir": out_dir}

    def _finish(self, seconds: float, out_dir: str) -> None:
        # paced by an Event, not a sleep poll: interruptible and lint-clean
        self._wakeup.wait(seconds)
        error: str | None = None
        try:
            _stop_trace()
        except Exception as e:
            error = f"{type(e).__name__}: {e}"
        with self._lock:
            done = self._running or {}
            self._running = None
            self._last = {
                "dir": out_dir,
                "seconds": seconds,
                "started": done.get("started"),
                "finished": time.time(),
                "error": error,
            }

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "running": self._running is not None,
                "current": dict(self._running) if self._running else None,
                "last": dict(self._last) if self._last else None,
            }


#: the process-wide controller — jax tracing is global, so one per process
PROFILER = ProfilerController()

#: last-seen pjit-cache size per registry, so a scrape can turn the size
#: gauge into a growth COUNTER (cache growth == fresh XLA compiles — the
#: scrape-level recompile signal that needs no call-site attribution)
_cache_size_seen: "weakref.WeakKeyDictionary[MetricsRegistry, int]" = (
    weakref.WeakKeyDictionary()
)

#: the per-device ``memory_stats`` walk crosses into the backend per device
#: — the one probe here whose cost scales with topology — so scrapes
#: arriving within this window reuse the cached gauge values instead of
#: re-walking (two Prometheus scrapers a second apart must not double the
#: backend chatter)
MEMSTATS_MIN_INTERVAL_S = 1.0

#: monotonic time of the last memory_stats walk, per registry
_memstats_last: "weakref.WeakKeyDictionary[MetricsRegistry, float]" = (
    weakref.WeakKeyDictionary()
)


def sample_runtime_gauges(registry: MetricsRegistry | None = None) -> bool:
    """Refresh JAX runtime gauges: live device buffers (count + bytes),
    per-device memory stats where the backend reports them (TPU does, CPU
    returns None), jit/pjit executable-cache entries PLUS their growth
    since the last scrape (``pio_jax_compile_cache_growth_total`` — cache
    growth is compiles happening), and the process-cumulative host<->device
    transfer tallies the device-efficiency layer keeps
    (``pio_device_transfer_bytes{direction}``).  Every probe is
    individually fenced — telemetry must never break a scrape — and the
    whole call is a no-op returning False unless jax is ALREADY imported in
    this process: a scrape of the admin/dashboard/event/storage daemons
    must not trigger a multi-second backend init (or contend for the TPU
    the serving process exclusively holds) just to report empty gauges.

    The call self-meters into ``pio_runtime_sample_seconds`` (this runs on
    EVERY scrape, so its cost must be a metric, not a guess), and the
    per-device ``memory_stats`` walk — the only probe whose cost scales
    with device count — is skipped when the previous walk was under
    :data:`MEMSTATS_MIN_INTERVAL_S` ago; the gauges simply keep their
    cached values between walks.
    """
    reg = registry or REGISTRY
    if "jax" not in sys.modules:
        return False
    try:
        import jax
    except Exception:
        return False
    t_start = time.perf_counter()
    try:
        arrs = jax.live_arrays()
        reg.gauge(
            "pio_jax_live_buffer_count", "Live jax.Array buffers in process"
        ).set(len(arrs))
        reg.gauge(
            "pio_jax_live_buffer_bytes", "Bytes held by live jax.Arrays"
        ).set(sum(getattr(a, "nbytes", 0) for a in arrs))
    except Exception:
        pass
    now = time.monotonic()
    last_walk = _memstats_last.get(reg)
    if last_walk is None or now - last_walk >= MEMSTATS_MIN_INTERVAL_S:
        _memstats_last[reg] = now
        try:
            fam = reg.gauge(
                "pio_jax_device_memory_bytes",
                "Backend-reported bytes in use per device",
                labelnames=("device",),
            )
            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                if stats and "bytes_in_use" in stats:
                    fam.labels(str(d.id)).set(stats["bytes_in_use"])
        except Exception:
            pass
    try:
        from jax._src import pjit as _pjit  # no public cache-size API yet

        size = 0
        for name in (
            "_cpp_pjit_cache_fun_only",
            "_cpp_pjit_cache_explicit_attributes",
        ):
            cache = getattr(_pjit, name, None)
            if cache is not None:
                size += cache.size()
        reg.gauge(
            "pio_jax_pjit_cache_entries",
            "Compiled executables held by the pjit caches",
        ).set(size)
        last = _cache_size_seen.get(reg)
        if last is not None and size > last:
            reg.counter(
                "pio_jax_compile_cache_growth_total",
                "pjit-cache entries added between scrapes (fresh compiles)",
            ).inc(size - last)
        _cache_size_seen[reg] = size
    except Exception:
        pass
    try:
        fam = reg.gauge(
            "pio_device_transfer_bytes",
            "Process-cumulative host<->device transfer bytes by direction",
            labelnames=("direction",),
        )
        for direction, total in device_obs.transfer_totals().items():
            fam.labels(direction).set(total)
    except Exception:
        pass
    reg.histogram(
        "pio_runtime_sample_seconds",
        "Cost of one sample_runtime_gauges pass (runs on every /metrics "
        "scrape)",
    ).observe(time.perf_counter() - t_start)
    return True
