"""Continuous host-path stack sampling: the always-available profiler.

The on-demand ``jax.profiler`` capture (obs/profiler.py) answers device
questions but needs a supported backend, a bounded window, and a tensorboard
viewer.  This module is the host-side complement: a daemon thread walks
``sys._current_frames()`` at a configurable rate (default 100 Hz),
aggregates every thread's stack into bounded folded-stack counts, and
exports them as collapsed-flamegraph text (``flamegraph.pl`` /
``inferno-flamegraph`` input) or speedscope JSON (https://speedscope.app) —
so "where is the host spending the solo path's ~100 ms" (ROADMAP item 3d)
is answerable on ANY backend, against a LIVE server, with no restart.

Threads are labeled by serving role (aio loop, executor workers,
MicroBatcher worker, lifecycle controller, HTTP serve threads, storage
daemon) so the flamegraph reads as the serving architecture, not a pile of
``Thread-7``\\ s.

Overhead is self-metered: every sampling pass's wall duration is timed
into ``pio_stack_sampler_seconds``, and the sampler thread's cumulative
CPU time (``time.thread_time`` — the GIL share the sampler actually
steals from serving threads; a pass's WALL time under load mostly counts
other threads' progress while the walk is preempted) over wall time is
reported as ``overhead_frac`` — tested <2 % of one core at 100 Hz.
Memory is bounded: at most ``max_stacks`` distinct (role, stack) keys are
retained; beyond that new stacks count into ``dropped`` instead of growing
the table.

Surfaces: debug-gated ``GET /debug/stacks.json`` (first request arms the
process sampler) and ``pio profile --stacks [--speedscope OUT.json]``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

#: sampling rate when none is configured (PIO_STACK_SAMPLER_HZ overrides)
DEFAULT_HZ = 100.0

#: hard bounds on the configurable rate — 1 kHz of frame walks would spend
#: the overhead budget on telemetry
MIN_HZ, MAX_HZ = 1.0, 500.0

#: distinct (role, stack) keys retained before new ones are dropped
DEFAULT_MAX_STACKS = 8192

#: frames walked per thread before the stack is truncated (deep recursion
#: must not make one pass unbounded)
MAX_FRAMES = 64

#: CO_GENERATOR | CO_COROUTINE | CO_ASYNC_GENERATOR — frames of these code
#: objects outlive a single call and get their ``f_back`` re-linked to
#: whichever caller resumes them, so the leaf cache must never trust them
_GEN_CO_FLAGS = 0x20 | 0x80 | 0x200

#: thread-name prefix/exact-name → serving role.  Ordered: first match wins.
_ROLE_RULES: tuple[tuple[str, str], ...] = (
    ("microbatch", "microbatcher"),
    ("pio-lifecycle", "lifecycle-controller"),
    ("pio-profiler", "profiler"),
    ("pio-cost-capture", "cost-capture"),
    ("pio-trace-fetch", "trace-fetch"),
    ("plugin-sniffers", "plugin-sniffers"),
    ("asyncio_", "executor-worker"),
    ("ThreadPoolExecutor", "executor-worker"),
    ("pio-executor", "executor-worker"),
    ("storage-server", "storage-daemon"),
    ("MainThread", "main"),
)


def thread_role(name: str) -> str:
    """Serving role for a thread name — the flamegraph's top-level frame."""
    for prefix, role in _ROLE_RULES:
        if name.startswith(prefix):
            return role
    if name.endswith("-aio"):
        return "aio-loop"
    if name.endswith("-http"):
        return "http-serve"
    if name.startswith("Thread-"):
        # ThreadingHTTPServer connection handlers get stdlib default names
        return "http-serve"
    return name


def _frame_label(code) -> str:
    """``func (file.py)`` — no line numbers, so one function is one frame
    regardless of which line the sample landed on."""
    return f"{code.co_name} ({os.path.basename(code.co_filename)})"


class StackSampler:
    """Daemon-thread wall-clock sampler over ``sys._current_frames()``.

    ``start()`` is idempotent; ``snapshot()`` / ``collapsed()`` /
    ``speedscope()`` read the aggregation without stopping it; ``reset()``
    clears counts but keeps sampling.  One instance per process is enough
    (:data:`SAMPLER`); tests build their own for isolation.
    """

    def __init__(
        self,
        hz: float | None = None,
        max_stacks: int = DEFAULT_MAX_STACKS,
        registry: MetricsRegistry | None = None,
    ):
        self._configured_hz = hz
        self.hz = hz or DEFAULT_HZ
        self.max_stacks = max_stacks
        self._registry = registry or REGISTRY
        self._lock = threading.Lock()
        #: (role, tuple-of-code-objects root-first) -> sample count
        self._counts: dict[tuple[str, tuple], int] = {}
        #: tid -> cached thread name (threading.enumerate() is per-pass
        #: cost otherwise; refreshed when an unknown tid appears)
        self._names: dict[int, str] = {}
        #: tid -> (leaf frame object, aggregation key).  A plain function
        #: frame's f_back chain is immutable for the frame object's
        #: lifetime and the labels carry no line numbers, so the SAME leaf
        #: frame object (thread blocked in a wait, or spinning inside one
        #: function) yields the same key without re-walking the stack —
        #: the steady state for most serving threads, and the difference
        #: between a ~0.5 % and a ~4 % sampling tax under 32-way load.
        #: Generator/coroutine leaf frames are exempt (never cached): they
        #: outlive calls and get f_back re-linked per resumption
        self._leaf_cache: dict[int, tuple[Any, tuple[str, tuple]]] = {}
        self._samples = 0
        self._dropped = 0
        self._sample_seconds = 0.0
        self._thread: threading.Thread | None = None
        self._stop_event = threading.Event()
        self._started_wall: float | None = None
        self._started_perf: float | None = None
        self._m_pass = self._registry.histogram(
            "pio_stack_sampler_seconds",
            "Duration of one stack-sampling pass over all threads",
        )

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "StackSampler":
        """Arm the sampler (idempotent, and atomic: two concurrent first
        requests to /debug/stacks.json both race here, and a double-start
        would double-count every stack forever).  The rate comes from the
        constructor, else ``PIO_STACK_SAMPLER_HZ``, else 100 Hz — read at
        start so a deploy script can tune a running image via env."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            hz = self._configured_hz
            if hz is None:
                try:
                    hz = float(
                        os.environ.get("PIO_STACK_SAMPLER_HZ", "") or 0
                    )
                except ValueError:
                    hz = 0.0
            self.hz = min(max(hz or DEFAULT_HZ, MIN_HZ), MAX_HZ)
            stop_event = threading.Event()
            self._stop_event = stop_event
            self._started_wall = time.time()
            self._started_perf = time.perf_counter()
            self._thread = threading.Thread(
                target=self._run,
                args=(stop_event,),
                name="pio-stack-sampler",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        # the event is captured here and passed to _run at start, so a
        # stop() racing a restart can only ever stop ITS thread — never a
        # freshly-started one observing a recycled event
        with self._lock:
            t = self._thread
            self._thread = None
            self._stop_event.set()
        if t is not None:
            t.join(timeout=2.0)

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples = 0
            self._dropped = 0
            self._sample_seconds = 0.0
            self._started_wall = time.time()
            self._started_perf = time.perf_counter()

    # -- the sampling loop ---------------------------------------------------

    def _run(self, stop_event: threading.Event) -> None:
        period = 1.0 / self.hz
        next_t = time.perf_counter() + period
        while not stop_event.is_set():
            t0 = time.perf_counter()
            c0 = time.thread_time()
            try:
                self._sample_once()
            except Exception:
                # a telemetry thread must never die on a transient (e.g. a
                # thread exiting mid-walk); skip the pass
                pass
            dt = time.perf_counter() - t0
            cpu = time.thread_time() - c0
            self._m_pass.observe(dt)
            with self._lock:
                self._sample_seconds += cpu
            delay = next_t - time.perf_counter()
            if delay <= 0:
                # overran the period (GC pause, huge thread count): re-anchor
                # instead of spinning to catch up
                next_t = time.perf_counter() + period
                delay = period
            else:
                next_t += period
            stop_event.wait(delay)

    def _sample_once(self) -> None:
        frames = sys._current_frames()
        names = self._names
        cache = self._leaf_cache
        if any(tid not in names for tid in frames):
            names.update((t.ident, t.name) for t in threading.enumerate())
        own = threading.get_ident()
        entries: list[tuple[str, tuple]] = []
        for tid, frame in frames.items():
            if tid == own:
                continue  # never sample the sampler
            reusable = not (frame.f_code.co_flags & _GEN_CO_FLAGS)
            if reusable:
                cached = cache.get(tid)
                if cached is not None and cached[0] is frame:
                    entries.append(cached[1])
                    continue
            codes = []
            append = codes.append
            f = frame
            depth = 0
            while f is not None and depth < MAX_FRAMES:
                append(f.f_code)
                f = f.f_back
                depth += 1
            codes.reverse()  # root first, leaf last (folded-stack order)
            role = thread_role(names.get(tid) or f"tid-{tid}")
            key = (role, tuple(codes))
            if reusable:
                cache[tid] = (frame, key)
            entries.append(key)
        if len(cache) > 2 * len(frames) + 8:
            # prune exited threads: a dead tid's cache entry pins its frame
            # (and that frame's locals) forever otherwise
            for tid in list(cache):
                if tid not in frames:
                    del cache[tid]
                    names.pop(tid, None)
        with self._lock:
            self._samples += 1
            counts = self._counts
            for key in entries:
                n = counts.get(key)
                if n is None:
                    if len(counts) >= self.max_stacks:
                        self._dropped += 1
                        continue
                    counts[key] = 1
                else:
                    counts[key] = n + 1

    # -- reads ---------------------------------------------------------------

    def _read(self) -> tuple[dict[tuple[str, tuple], int], int, int, float]:
        with self._lock:
            return (
                dict(self._counts),
                self._samples,
                self._dropped,
                self._sample_seconds,
            )

    def snapshot(self) -> dict[str, Any]:
        """The ``/debug/stacks.json`` body (sans the stack texts): sampler
        state, self-metered overhead, and per-role sample totals."""
        counts, samples, dropped, sample_s = self._read()
        elapsed = (
            time.perf_counter() - self._started_perf
            if self._started_perf is not None
            else 0.0
        )
        roles: dict[str, int] = {}
        for (role, _), n in counts.items():
            roles[role] = roles.get(role, 0) + n
        return {
            "running": self.running,
            "hz": self.hz,
            "samples": samples,
            "distinct_stacks": len(counts),
            "max_stacks": self.max_stacks,
            "dropped_stacks": dropped,
            "duration_s": round(elapsed, 3),
            #: sampler-thread CPU seconds — the GIL share sampling stole
            "sample_seconds_total": round(sample_s, 6),
            #: the self-meter: fraction of one core spent sampling
            "overhead_frac": round(sample_s / elapsed, 6) if elapsed > 0 else 0.0,
            "started_at": self._started_wall,
            "threads": dict(sorted(roles.items())),
        }

    def collapsed(self) -> str:
        """Collapsed flamegraph text: ``role;frame;frame;... count`` lines,
        role as the root frame — pipe into flamegraph.pl / inferno."""
        counts, _, _, _ = self._read()
        lines = []
        for (role, codes), n in counts.items():
            stack = ";".join([role] + [_frame_label(c) for c in codes])
            lines.append(f"{stack} {n}")
        return "\n".join(sorted(lines)) + ("\n" if lines else "")

    def speedscope(self) -> dict[str, Any]:
        """Speedscope file-format JSON: one sampled profile per thread role
        (weights in seconds — count × sampling period), loadable at
        https://speedscope.app with zero build steps."""
        counts, samples, _, _ = self._read()
        period = 1.0 / self.hz if self.hz else 0.0
        frame_index: dict[str, int] = {}
        frames: list[dict[str, str]] = []

        def fidx(label: str) -> int:
            i = frame_index.get(label)
            if i is None:
                i = frame_index[label] = len(frames)
                frames.append({"name": label})
            return i

        by_role: dict[str, list[tuple[tuple, int]]] = {}
        for (role, codes), n in counts.items():
            by_role.setdefault(role, []).append((codes, n))
        profiles = []
        for role in sorted(by_role):
            stacks = by_role[role]
            sample_rows = [
                [fidx(_frame_label(c)) for c in codes] for codes, _ in stacks
            ]
            weights = [n * period for _, n in stacks]
            profiles.append(
                {
                    "type": "sampled",
                    "name": role,
                    "unit": "seconds",
                    "startValue": 0,
                    "endValue": round(sum(weights), 6),
                    "samples": sample_rows,
                    "weights": weights,
                }
            )
        return {
            "$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": profiles,
            "name": f"pio host stacks ({samples} samples @ {self.hz:g} Hz)",
            "activeProfileIndex": 0,
            "exporter": "predictionio_tpu",
        }


#: the process sampler — armed by the first /debug/stacks.json request (or
#: explicitly via StackSampler.start / `pio profile --stacks` locally)
SAMPLER = StackSampler()
