"""Flight recorder: keep the requests worth debugging.

A metrics histogram tells you p99 moved; it cannot tell you *which* request
moved it.  The flight recorder retains full per-request records — span tree,
queue-wait/device split, payload sizes, error text — for the N slowest
requests plus every errored one, served at ``GET /debug/flight.json``.
Bounded memory: a min-heap of the slowest N and a ring of recent errors.

Handlers attach request-scoped detail (the MicroBatcher's per-item timing,
wave size) through :func:`annotate`, a contextvar dict the HTTP front end
folds into the entry when the request finishes — no plumbing through return
values.
"""

from __future__ import annotations

import contextvars
import heapq
import threading
import time
from collections import deque
from typing import Any

#: request-scoped annotations merged into the flight entry at finish
_annotations_var: contextvars.ContextVar[dict[str, Any] | None] = (
    contextvars.ContextVar("pio_flight_annotations", default=None)
)


def begin_annotations() -> contextvars.Token:
    """Open a fresh annotation scope for the current request."""
    return _annotations_var.set({})


def end_annotations(token: contextvars.Token) -> None:
    _annotations_var.reset(token)


def annotate(**fields: Any) -> None:
    """Attach fields to the in-flight request's flight entry (no-op when no
    request scope is open, e.g. unit-testing a handler directly)."""
    d = _annotations_var.get()
    if d is not None:
        d.update(fields)


def current_annotations() -> dict[str, Any]:
    return dict(_annotations_var.get() or {})


class FlightRecorder:
    """Retain the slowest and the errored requests, bounded.

    ``record(entry)`` takes a flat dict (request_id, route, status,
    duration_s, span, ...).  Entries with status >= 500 or an ``error``
    field land in the error ring (newest evicts oldest); every entry
    competes for the slowest-N heap by ``duration_s``.
    """

    def __init__(self, keep_slowest: int = 32, keep_errors: int = 64):
        self.keep_slowest = keep_slowest
        self._lock = threading.Lock()
        #: min-heap of (duration_s, seq, entry) — root is the fastest of
        #: the slow set, so a new slower entry replaces it in O(log N)
        self._slowest: list[tuple[float, int, dict[str, Any]]] = []
        self._errors: deque[dict[str, Any]] = deque(maxlen=keep_errors)
        self._seq = 0
        self._total = 0

    def would_retain(self, duration_s: float) -> bool:
        """Lock-free pre-check: would a non-errored entry of this duration
        enter the slowest-N heap?  Callers use it to skip building the
        (span-tree-serializing) entry for unremarkable requests; the answer
        is approximate under concurrency, which only risks one extra build.
        """
        slowest = self._slowest
        return len(slowest) < self.keep_slowest or duration_s > slowest[0][0]

    def record(self, entry: dict[str, Any]) -> None:
        duration = float(entry.get("duration_s") or 0.0)
        errored = entry.get("error") is not None or (
            int(entry.get("status") or 0) >= 500
        )
        with self._lock:
            self._seq += 1
            self._total += 1
            entry.setdefault("time", round(time.time(), 3))
            if errored:
                self._errors.append(entry)
            item = (duration, self._seq, entry)
            if len(self._slowest) < self.keep_slowest:
                heapq.heappush(self._slowest, item)
            elif duration > self._slowest[0][0]:
                heapq.heapreplace(self._slowest, item)

    def snapshot(
        self,
        request_id: str | None = None,
        limit: int | None = None,
        trace_id: str | None = None,
    ) -> dict[str, Any]:
        """Slowest (descending duration) and errored (newest first);
        ``trace_id`` filters to one cross-process trace's entries (the
        click-through from an SLO exemplar or an assembled timeline)."""
        with self._lock:
            slowest = [e for _, _, e in sorted(self._slowest, reverse=True)]
            errors = list(self._errors)[::-1]
            total = self._total
        if request_id is not None:
            slowest = [e for e in slowest if e.get("request_id") == request_id]
            errors = [e for e in errors if e.get("request_id") == request_id]
        if trace_id is not None:
            slowest = [e for e in slowest if e.get("trace_id") == trace_id]
            errors = [e for e in errors if e.get("trace_id") == trace_id]
        if limit is not None:
            slowest, errors = slowest[:limit], errors[:limit]
        return {"recorded_total": total, "slowest": slowest, "errors": errors}

    def clear(self) -> None:
        with self._lock:
            self._slowest.clear()
            self._errors.clear()
            self._total = 0


#: process-default recorder (apps may hold their own for test isolation)
FLIGHT = FlightRecorder()
