"""Device-efficiency observability: roofline attribution from real XLA
costs, recompile accounting, and the perf-regression gate.

The serving/training substrate already times everything (spans, MicroBatcher
waves) but none of those numbers say how well the *device* is used: BENCH_r05
achieves 25 GB/s of an ~819 GB/s HBM peak and the repo's only roofline math
is ad-hoc arithmetic inside bench.py.  This module is the runtime
counterpart:

- :func:`jit_cost_analysis` captures ``lowered.compile().cost_analysis()``
  (FLOPs, bytes accessed) for a jitted entry point — the XLA cost model's
  own numbers, not estimates;
- :class:`EfficiencyTracker` joins those costs with the wall-clock the
  callers already measure and exports live achieved-vs-peak gauges
  (``pio_device_achieved_gbps{fn}``, ``pio_device_achieved_tflops{fn}``,
  ``pio_device_utilization_frac{fn,resource}``) against a per-platform
  peak table (:func:`device_peaks`, overridable via
  ``PIO_DEVICE_PEAK_GBPS`` / ``PIO_DEVICE_PEAK_TFLOPS``);
- :class:`RecompileTracker` counts compiles per (fn, abstract-shape
  signature) and detects recompile *storms* — many distinct signatures for
  one fn inside a sliding window, the runtime counterpart of the
  PIO-JAX004 static rule (a client sweeping ``num`` through the NCF wave
  path churns the padded top-k width and recompiles per value);
- a contextvar *wave timeline* (:func:`wave_timeline` / :func:`wave_stage`)
  lets engines split a MicroBatcher wave's opaque ``device_s`` into
  host-gather / H2D / device-compute / D2H, so a slow query is attributable
  to transfer vs compute vs queue;
- :func:`als_plan_roofline` is the pallas-plan HBM/MXU arithmetic that used
  to live in bench.py, and :func:`compare_bench` is the
  ``pio bench --compare`` regression gate over two BENCH json lines
  (``schema_version``-checked).

Import-light by design: servers that never touch an accelerator (event
ingest, admin, dashboard) import this module through ``obs.http`` — nothing
here imports jax at module scope, and every jax probe is gated on jax
already being in ``sys.modules`` (the same no-TPU-init guarantee
``obs.profiler`` keeps).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import statistics
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Mapping

from predictionio_tpu.obs.metrics import (
    REGISTRY,
    STAGE_BUCKETS,
    MetricsRegistry,
)

log = logging.getLogger("predictionio_tpu.device")

# ---------------------------------------------------------------------------
# peak table

#: Published peak HBM bandwidth (GB/s) and dense-matmul throughput (TFLOP/s,
#: bf16 for TPUs) per device kind, most specific prefix wins.  The CPU row is
#: a DDR-class placeholder so utilization fractions stay meaningful (and
#: test-assertable) on the CPU backend; override per deployment with
#: PIO_DEVICE_PEAK_GBPS / PIO_DEVICE_PEAK_TFLOPS.
PEAK_TABLE: dict[str, tuple[float, float]] = {
    "tpu v4": (1228.0, 275.0),
    "tpu v5 lite": (819.0, 197.0),
    "tpu v5e": (819.0, 197.0),
    "tpu v5p": (2765.0, 459.0),
    "tpu": (819.0, 197.0),  # unrecognized TPU: assume the v5e class
    "cpu": (25.0, 0.5),
    "gpu": (900.0, 100.0),
}


@dataclass(frozen=True)
class DevicePeaks:
    """Peak rates one ``achieved / peak`` division away from a fraction."""

    hbm_gbps: float
    tflops: float
    source: str  # table key, "env", or "default"


def _platform_kind() -> str:
    """Best-effort device-kind string WITHOUT initializing a backend: jax is
    only consulted when the process already imported it.  Falls back from
    the device kind to the platform name when the kind matches no peak row
    (CUDA kinds are GPU model names like 'nvidia a100...', which must land
    on the 'gpu' row, not the cpu fallback)."""
    if "jax" not in sys.modules:
        return "cpu"
    try:
        import jax

        d = jax.devices()[0]
        kind = str(getattr(d, "device_kind", "") or "").lower()
        if kind and any(kind.startswith(p) for p in PEAK_TABLE):
            return kind
        return str(d.platform).lower() or "cpu"
    except Exception:
        return "cpu"


def device_peaks(kind: str | None = None) -> DevicePeaks:
    """Resolve the peak row for ``kind`` (default: the live platform).

    ``PIO_DEVICE_PEAK_GBPS`` / ``PIO_DEVICE_PEAK_TFLOPS`` override the table
    per deployment — read at call time so an operator can correct a
    co-tenanted or down-clocked chip without a restart.
    """
    kind = (kind or _platform_kind()).lower()
    gbps = tflops = None
    source = "default"
    for prefix in sorted(PEAK_TABLE, key=len, reverse=True):
        if kind.startswith(prefix):
            gbps, tflops = PEAK_TABLE[prefix]
            source = prefix
            break
    if gbps is None:
        gbps, tflops = PEAK_TABLE["cpu"]
    env_gbps = os.environ.get("PIO_DEVICE_PEAK_GBPS")
    env_tflops = os.environ.get("PIO_DEVICE_PEAK_TFLOPS")
    if env_gbps or env_tflops:
        # source flips to "env" only when an override actually parsed — a
        # typo'd value must not make the snapshot CLAIM a correction that
        # was silently ignored
        try:
            gbps = float(env_gbps) if env_gbps else gbps
            source = "env" if env_gbps else source
        except ValueError:
            pass
        try:
            tflops = float(env_tflops) if env_tflops else tflops
            source = "env" if env_tflops else source
        except ValueError:
            pass
    return DevicePeaks(hbm_gbps=float(gbps), tflops=float(tflops),
                       source=source)


def achieved_gbps(bytes_moved: float, seconds: float) -> float:
    """Achieved HBM bandwidth in GB/s for ``bytes_moved`` over ``seconds``."""
    return bytes_moved / seconds / 1e9 if seconds > 0 else 0.0


def achieved_tflops(flops: float, seconds: float) -> float:
    """Achieved TFLOP/s for ``flops`` executed over ``seconds``."""
    return flops / seconds / 1e12 if seconds > 0 else 0.0


def utilization_frac(achieved: float, peak: float) -> float:
    """``achieved / peak`` with a zero-peak guard (fractions, not %)."""
    return achieved / peak if peak > 0 else 0.0


def device_label(x: Any) -> str:
    """``platform:id`` label of the device holding ``x`` (a jax array), or
    ``"host"`` when it has no device set — safe on plain numpy."""
    try:
        devices = getattr(x, "devices", None)
        if devices is None:
            return "host"
        d = next(iter(devices()))
        return f"{d.platform}:{d.id}"
    except Exception:
        return "host"


# ---------------------------------------------------------------------------
# XLA cost capture


def jit_cost_analysis(jitted: Any, *args: Any, **kwargs: Any) -> dict | None:
    """FLOPs / bytes-accessed of one jitted call, from XLA's own cost model.

    Runs the AOT path (``jitted.lower(...).compile().cost_analysis()``) for
    the given concrete arguments.  That compile is out-of-band — it does NOT
    populate the jit cache — so callers cache the result per abstract-shape
    signature (:meth:`EfficiencyTracker.capture_cost`) and only pay it once
    per signature, the same cardinality the jit cache itself grows at (and
    the persistent compilation cache, when configured, absorbs the repeat).
    Returns ``{"flops": float, "bytes": float}`` or None when the backend
    reports no cost model; never raises — telemetry must not break serving.
    """
    try:
        lowered = jitted.lower(*args, **kwargs)
        analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if not isinstance(analysis, Mapping):
            return None
        flops = float(analysis.get("flops", 0.0) or 0.0)
        nbytes = float(analysis.get("bytes accessed", 0.0) or 0.0)
        if flops <= 0.0 and nbytes <= 0.0:
            return None
        return {"flops": flops, "bytes": nbytes}
    except Exception:
        return None


def signature_of(*args: Any) -> tuple:
    """Abstract-shape signature of concrete call args: ``(shape, dtype)``
    for array-likes, ``repr`` for everything else — the recompile key."""
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is not None:
            sig.append((tuple(shape), str(getattr(a, "dtype", "?"))))
        else:
            sig.append(repr(a))
    return tuple(sig)


# ---------------------------------------------------------------------------
# efficiency tracker


class EfficiencyTracker:
    """Join per-fn XLA costs with caller-measured device seconds.

    ``record_cost`` stores FLOPs/bytes per (fn, signature) — from
    :func:`jit_cost_analysis` or an analytic plan (the pallas roofline) —
    and ``observe`` converts one timed execution into achieved-vs-peak
    gauges plus cumulative FLOP/byte counters.  All state under one lock;
    the observe path is two dict reads and four gauge sets.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        peaks: DevicePeaks | None = None,
    ):
        self._lock = threading.Lock()
        self._registry = registry or REGISTRY
        self._peaks = peaks
        #: (fn, signature) -> {"flops", "bytes", "source"}
        self._costs: dict[tuple[str, tuple], dict[str, Any]] = {}
        #: (fn, signature) -> in-flight deferred capture thread
        self._pending: dict[tuple[str, tuple], threading.Thread] = {}
        #: fn -> the signature of the most recent record/observe
        self._last_sig: dict[str, tuple] = {}
        #: fn -> {"calls", "seconds", "flops", "bytes"} cumulative
        self._totals: dict[str, dict[str, float]] = {}
        reg = self._registry
        self._g_gbps = reg.gauge(
            "pio_device_achieved_gbps",
            "Achieved HBM bandwidth per jitted entry point (GB/s)",
            labelnames=("fn",),
        )
        self._g_tflops = reg.gauge(
            "pio_device_achieved_tflops",
            "Achieved matmul throughput per jitted entry point (TFLOP/s)",
            labelnames=("fn",),
        )
        self._g_util = reg.gauge(
            "pio_device_utilization_frac",
            "Achieved / peak fraction per entry point and resource",
            labelnames=("fn", "resource"),
        )
        self._c_flops = reg.counter(
            "pio_device_flops_total",
            "Cumulative FLOPs executed per entry point (cost-model)",
            labelnames=("fn",),
        )
        self._c_bytes = reg.counter(
            "pio_device_bytes_total",
            "Cumulative bytes accessed per entry point (cost-model)",
            labelnames=("fn",),
        )

    def record_cost(
        self,
        fn: str,
        flops: float,
        nbytes: float,
        signature: tuple = (),
        source: str = "cost_analysis",
    ) -> None:
        """Install the per-call cost of ``fn`` at ``signature``."""
        with self._lock:
            self._costs[(fn, signature)] = {
                "flops": float(flops),
                "bytes": float(nbytes),
                "source": source,
            }
            self._last_sig[fn] = signature

    def capture_cost(
        self, fn: str, jitted: Any, *args: Any,
        signature: tuple | None = None, defer: bool = False, **kwargs: Any,
    ) -> dict | None:
        """Capture ``fn``'s XLA cost ONCE per signature (cached thereafter).

        Returns the cost dict (possibly cached) or None when the backend has
        no cost model.  The once-per-signature discipline keeps the AOT
        compile off the steady-state hot path.

        ``defer=True`` (the serving-path mode) runs the first capture on a
        daemon thread and returns None immediately: the out-of-band AOT
        analysis compile must not stall a wave under its deadline — it runs
        CONCURRENTLY with the jit cache's own compile of the same signature,
        and the cost lands before the next wave of that shape.  Tests drain
        with :meth:`flush`.
        """
        sig = signature_of(*args) if signature is None else signature
        key = (fn, sig)
        with self._lock:
            cached = self._costs.get(key)
            if cached is not None:
                self._last_sig[fn] = sig
                return dict(cached)
            if defer and key in self._pending:
                return None
        if defer:

            def work() -> None:
                try:
                    cost = jit_cost_analysis(jitted, *args, **kwargs)
                    if cost is not None:
                        self.record_cost(
                            fn, cost["flops"], cost["bytes"], signature=sig
                        )
                finally:
                    with self._lock:
                        self._pending.pop(key, None)

            thread = threading.Thread(
                target=work, name="pio-cost-capture", daemon=True
            )
            # locked RE-check before insert: the cheap check above dropped
            # the lock (so the steady-state cache-hit path allocates no
            # Thread), and two concurrent first waves must not both spawn
            # capture threads — the loser's cleanup would pop the winner's
            # _pending entry and flush() would return early
            with self._lock:
                cached = self._costs.get(key)
                if cached is not None:
                    self._last_sig[fn] = sig
                    return dict(cached)
                if key in self._pending:
                    return None
                self._pending[key] = thread
            thread.start()
            return None
        cost = jit_cost_analysis(jitted, *args, **kwargs)
        if cost is None:
            return None
        self.record_cost(fn, cost["flops"], cost["bytes"], signature=sig)
        with self._lock:
            return dict(self._costs[key])

    def cached_cost(self, fn: str, signature: tuple) -> dict | None:
        """The recorded cost for (fn, signature), if it has landed."""
        with self._lock:
            cost = self._costs.get((fn, signature))
            return dict(cost) if cost is not None else None

    def flush(self, timeout: float = 10.0) -> bool:
        """Join outstanding deferred captures (tests and batch callers);
        True when none remain in flight."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                threads = list(self._pending.values())
            if not threads:
                return True
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            threads[0].join(remaining)

    def observe(
        self, fn: str, seconds: float, signature: tuple | None = None
    ) -> None:
        """One timed execution of ``fn``: update achieved/utilization gauges
        and cumulative counters using the cost recorded for ``signature``
        (default: the most recent one for ``fn``).  No-op without a cost —
        timing alone cannot place a point on the roofline."""
        if seconds <= 0:
            return
        with self._lock:
            sig = self._last_sig.get(fn) if signature is None else signature
            cost = self._costs.get((fn, sig if sig is not None else ()))
            if cost is None:
                return
            totals = self._totals.setdefault(
                fn, {"calls": 0.0, "seconds": 0.0, "flops": 0.0, "bytes": 0.0}
            )
            totals["calls"] += 1
            totals["seconds"] += seconds
            totals["flops"] += cost["flops"]
            totals["bytes"] += cost["bytes"]
        gbps = achieved_gbps(cost["bytes"], seconds)
        tflops = achieved_tflops(cost["flops"], seconds)
        peaks = self._peaks or device_peaks()
        self._g_gbps.labels(fn).set(gbps)
        self._g_tflops.labels(fn).set(tflops)
        self._g_util.labels(fn, "hbm").set(
            utilization_frac(gbps, peaks.hbm_gbps)
        )
        self._g_util.labels(fn, "mxu").set(
            utilization_frac(tflops, peaks.tflops)
        )
        self._c_flops.labels(fn).inc(cost["flops"])
        self._c_bytes.labels(fn).inc(cost["bytes"])

    def snapshot(self) -> dict[str, Any]:
        """Per-fn costs, cumulative achieved rates, and utilization — the
        ``/efficiency.json`` body."""
        peaks = self._peaks or device_peaks()
        with self._lock:
            costs = {k: dict(v) for k, v in self._costs.items()}
            totals = {k: dict(v) for k, v in self._totals.items()}
        fns: dict[str, Any] = {}
        for (fn, _sig), cost in costs.items():
            entry = fns.setdefault(
                fn,
                {
                    "signatures": 0,
                    "flops_per_call": 0.0,
                    "bytes_per_call": 0.0,
                    "source": cost["source"],
                },
            )
            entry["signatures"] += 1
            # the largest signature's cost is the representative one
            entry["flops_per_call"] = max(
                entry["flops_per_call"], cost["flops"]
            )
            entry["bytes_per_call"] = max(
                entry["bytes_per_call"], cost["bytes"]
            )
        for fn, t in totals.items():
            entry = fns.setdefault(fn, {"signatures": 0, "source": "?"})
            gbps = achieved_gbps(t["bytes"], t["seconds"])
            tflops = achieved_tflops(t["flops"], t["seconds"])
            entry.update(
                calls=int(t["calls"]),
                seconds_total=round(t["seconds"], 6),
                flops_total=t["flops"],
                bytes_total=t["bytes"],
                achieved_gbps=round(gbps, 3),
                achieved_tflops=round(tflops, 6),
                utilization_hbm=round(
                    utilization_frac(gbps, peaks.hbm_gbps), 6
                ),
                utilization_mxu=round(
                    utilization_frac(tflops, peaks.tflops), 6
                ),
            )
        return {
            "platform": _platform_kind(),
            "peaks": {
                "hbm_gbps": peaks.hbm_gbps,
                "tflops": peaks.tflops,
                "source": peaks.source,
            },
            "functions": fns,
        }


# ---------------------------------------------------------------------------
# recompile accounting


class RecompileTracker:
    """Compile events keyed by (fn, abstract-shape signature), with a storm
    detector: N distinct signatures for one fn inside a sliding window means
    traffic is churning shapes and every wave pays an XLA compile — the
    runtime counterpart of the PIO-JAX004 static rule.

    Thresholds come from ``PIO_RECOMPILE_STORM_N`` (distinct signatures,
    default 4) and ``PIO_RECOMPILE_STORM_WINDOW_S`` (default 60) at
    construction.  ``now`` parameters exist so tests drive a frozen clock.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        storm_threshold: int | None = None,
        window_s: float | None = None,
    ):
        self._lock = threading.Lock()
        if storm_threshold is None:
            storm_threshold = int(
                os.environ.get("PIO_RECOMPILE_STORM_N", "4")
            )
        if window_s is None:
            window_s = float(
                os.environ.get("PIO_RECOMPILE_STORM_WINDOW_S", "60")
            )
        self.storm_threshold = max(storm_threshold, 2)
        self.window_s = window_s
        #: fn -> every signature ever seen (compile-cache cardinality)
        self._seen: dict[str, set] = {}
        #: fn -> deque of (t, signature) for NEW signatures in the window
        self._recent: dict[str, deque] = {}
        #: fn -> storm-active-until timestamp
        self._storm_until: dict[str, float] = {}
        reg = registry or REGISTRY
        self._c_recompiles = reg.counter(
            "pio_jax_recompile_total",
            "New (fn, abstract shapes) signatures seen — one per XLA compile",
            labelnames=("fn",),
        )
        self._c_storms = reg.counter(
            "pio_recompile_storm_total",
            "Recompile storms detected (distinct signatures over threshold "
            "inside the window)",
            labelnames=("fn",),
        )

    def note_signature(
        self, fn: str, signature: tuple, now: float | None = None
    ) -> bool:
        """Record a call signature; returns True when it is NEW for ``fn``
        (i.e. this call compiled).  Trips the storm counter + a structured
        warning when distinct new signatures inside the window reach the
        threshold."""
        t = time.monotonic() if now is None else now
        with self._lock:
            seen = self._seen.setdefault(fn, set())
            if signature in seen:
                return False
            seen.add(signature)
            recent = self._recent.setdefault(fn, deque())
            recent.append((t, signature))
            while recent and recent[0][0] < t - self.window_s:
                recent.popleft()
            distinct = len(recent)
            storming = distinct >= self.storm_threshold
            was_storming = self._storm_until.get(fn, 0.0) > t
            if storming:
                self._storm_until[fn] = t + self.window_s
        self._c_recompiles.labels(fn).inc()
        if storming and not was_storming:
            self._c_storms.labels(fn).inc()
            log.warning(
                "recompile storm: %d distinct shape signatures for %s "
                "inside %.0fs — traffic is churning shapes and every wave "
                "pays an XLA compile (pad inputs to a fixed menu of shapes; "
                "see PIO-JAX004)",
                distinct,
                fn,
                self.window_s,
                extra={
                    "fn": fn,
                    "distinct_signatures": distinct,
                    "window_s": self.window_s,
                },
            )
        return True

    def active_storms(self, now: float | None = None) -> dict[str, Any]:
        """Functions currently inside a storm window.  ``signatures`` is the
        IN-WINDOW distinct count the storm was detected on (what the
        operator warning cites); ``total_signatures`` the lifetime tally."""
        t = time.monotonic() if now is None else now
        with self._lock:
            return {
                fn: {
                    "until_s": round(until - t, 3),
                    "signatures": len(
                        [1 for ts, _ in self._recent.get(fn, ())
                         if ts >= t - self.window_s]
                    ),
                    "total_signatures": len(self._seen.get(fn, ())),
                }
                for fn, until in self._storm_until.items()
                if until > t
            }

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        with self._lock:
            fns = {
                fn: {
                    "signatures": len(sigs),
                    "recent_window": len(self._recent.get(fn, ())),
                }
                for fn, sigs in self._seen.items()
            }
        return {
            "threshold": self.storm_threshold,
            "window_s": self.window_s,
            "functions": fns,
            "active_storms": self.active_storms(now),
        }


# ---------------------------------------------------------------------------
# wave timeline: the 4-way device_s split

#: the stages a wave decomposes into; anything unattributed lands in "other"
WAVE_STAGES: tuple[str, ...] = ("host_gather", "h2d", "compute", "d2h")


class WaveTimeline:
    """Per-wave accumulator engines mark stages into (contextvar-scoped)."""

    __slots__ = (
        "stages", "device", "fn", "flops", "bytes", "transfers", "shards",
        "shard_seconds", "cache_hits", "cache_misses", "cache_miss_bytes",
        "storage_bytes",
    )

    def __init__(self):
        self.stages: dict[str, float] = {}
        self.device: str = "host"
        self.fn: str | None = None
        self.flops: float = 0.0
        self.bytes: float = 0.0
        self.transfers: dict[str, float] = {}
        #: factor-cache hits inside this wave (note_cache_hit): a repeat
        #: entity whose gather was skipped — flows into per-item meta as
        #: ``cache_hits`` so flight entries prove gather ~ 0 on a hit
        self.cache_hits: int = 0
        #: ... and the misses, with the bytes their resolving fetch moved
        #: (note_cache_miss / note_cache_fill): the cost ledger bills a hit
        #: as ≈0 bytes and a miss as its fetch bytes (obs/costs.py)
        self.cache_misses: int = 0
        self.cache_miss_bytes: float = 0.0
        #: event-store bytes read inside this wave (costs.note_storage_read
        #: lands here when no request record is bound — the wave total is
        #: prorated back to members through per-item meta)
        self.storage_bytes: float = 0.0
        #: per-device byte/shard attribution of a SHARDED wave (filled by
        #: note_wave_shards; flows into per-item meta -> flight entries)
        self.shards: dict[str, dict[str, float]] = {}
        #: per-device settle seconds of a SHARDED wave (filled by
        #: note_shard_seconds; the straggler board's and the distributed
        #: timeline's per-shard signal)
        self.shard_seconds: dict[str, float] = {}


_timeline_var: contextvars.ContextVar[WaveTimeline | None] = (
    contextvars.ContextVar("pio_wave_timeline", default=None)
)

#: process-cumulative transfer byte tallies (mirrored to gauges on scrape by
#: obs.profiler.sample_runtime_gauges so isolated registries see them too)
_transfer_lock = threading.Lock()
_transfer_totals: dict[str, float] = {"h2d": 0.0, "d2h": 0.0}


@contextlib.contextmanager
def wave_timeline():
    """Open a wave scope; the MicroBatcher wraps ``batch_fn`` in one so the
    engine's :func:`wave_stage` marks land on the dispatching wave."""
    tl = WaveTimeline()
    token = _timeline_var.set(tl)
    try:
        yield tl
    finally:
        _timeline_var.reset(token)


def current_timeline() -> WaveTimeline | None:
    return _timeline_var.get()


@contextlib.contextmanager
def wave_stage(name: str):
    """Time a block into the current wave's ``name`` stage (no-op without an
    open timeline, e.g. an engine's batch_predict called outside serving)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        tl = _timeline_var.get()
        if tl is not None:
            tl.stages[name] = (
                tl.stages.get(name, 0.0) + time.perf_counter() - t0
            )


def note_wave_device(label: str) -> None:
    """Attach the executing device's label to the current wave."""
    tl = _timeline_var.get()
    if tl is not None:
        tl.device = label


def note_cache_hit(n: int = 1) -> None:
    """Record ``n`` factor-cache hits on the current wave (no-op outside a
    wave scope) — the per-request twin of pio_factor_cache_hits_total."""
    tl = _timeline_var.get()
    if tl is not None:
        tl.cache_hits += n


def note_cache_miss(n: int = 1) -> None:
    """Record ``n`` factor-cache misses on the current wave — each one paid
    the real gather its hit-twin skipped."""
    tl = _timeline_var.get()
    if tl is not None:
        tl.cache_misses += n


def note_cache_fill(nbytes: float) -> None:
    """Record the bytes a cache-miss fetch moved into the cache on the
    current wave (the miss side of the cost ledger's hit-vs-miss split)."""
    tl = _timeline_var.get()
    if tl is not None:
        tl.cache_miss_bytes += float(nbytes)


def note_wave_cost(fn: str, cost: Mapping[str, float] | None) -> None:
    """Attach the wave's entry-point name and per-call cost (flows into the
    flight-recorder entry of any slow/errored request the wave served)."""
    tl = _timeline_var.get()
    if tl is not None:
        tl.fn = fn
        if cost:
            tl.flops = float(cost.get("flops", 0.0))
            tl.bytes = float(cost.get("bytes", 0.0))


def note_wave_shards(attribution: Mapping[str, Mapping[str, float]]) -> None:
    """Attach a sharded wave's per-device attribution (the
    ``parallel.mesh.meter_shards`` map) to the current timeline: every
    flight entry of a sharded wave answers "which devices participated and
    how many bytes each held"."""
    tl = _timeline_var.get()
    if tl is not None and attribution:
        tl.shards = {k: dict(v) for k, v in attribution.items()}


def note_shard_seconds(shard_seconds: Mapping[str, float]) -> None:
    """Attach a sharded wave's per-device settle seconds to the current
    timeline (flows into per-item meta as ``wave_shard_seconds`` and the
    distributed timeline's per-shard device tracks)."""
    tl = _timeline_var.get()
    if tl is not None and shard_seconds:
        tl.shard_seconds = {k: float(v) for k, v in shard_seconds.items()}


def note_transfer(
    direction: str, nbytes: int, registry: MetricsRegistry | None = None
) -> None:
    """Account ``nbytes`` moved host<->device (``h2d`` / ``d2h``): bumps the
    process tally + the registry counter, and the current wave's split."""
    with _transfer_lock:
        _transfer_totals[direction] = (
            _transfer_totals.get(direction, 0.0) + nbytes
        )
    (registry or REGISTRY).counter(
        "pio_device_transfer_bytes_total",
        "Cumulative host<->device transfer bytes by direction",
        labelnames=("direction",),
    ).labels(direction).inc(nbytes)
    tl = _timeline_var.get()
    if tl is not None:
        tl.transfers[direction] = tl.transfers.get(direction, 0.0) + nbytes


def transfer_totals() -> dict[str, float]:
    """Process-cumulative h2d/d2h byte tallies (scrape-time mirror)."""
    with _transfer_lock:
        return dict(_transfer_totals)


def split_breakdown(
    tl: WaveTimeline | None, device_s: float
) -> dict[str, float]:
    """Decompose ``device_s`` into the 4 marked stages plus ``other`` (the
    unattributed remainder, clamped at zero) — the parts sum to ``device_s``
    whenever the marked stages fit inside it, which they do by construction
    (stages are timed inside the batch_fn window ``device_s`` brackets)."""
    stages = dict(tl.stages) if tl is not None else {}
    out = {name: round(stages.get(name, 0.0), 6) for name in WAVE_STAGES}
    marked = sum(stages.get(name, 0.0) for name in WAVE_STAGES)
    out["other"] = round(max(device_s - marked, 0.0), 6)
    return out


# ---------------------------------------------------------------------------
# ALS pallas-plan roofline (moved out of bench.py so bench consumes it)


def als_plan_roofline(plan: Mapping[str, Any]) -> dict[str, float] | None:
    """HBM bytes and MXU flop-equivalents per ALS iteration from the staged
    pallas plan (``ops.als.LAST_PLAN_INFO``) — the analytic roofline for the
    kernel the XLA cost model cannot see inside (pallas bodies are opaque to
    ``cost_analysis``).  Returns per-iteration ``gb`` / ``tflop_eq`` or None
    when the plan is missing the required fields."""
    required = ("width", "rank", "precision", "rows_user", "rows_item",
                "blocks_user", "blocks_item")
    if not all(k in plan for k in required):
        return None
    width = plan["width"]
    passes = {"hilo": 2, "bf16": 1, "highest": 6}.get(plan["precision"])
    if passes is None:
        return None
    row_b = width * 4
    k_pad = (plan["rank"] + 7) // 8 * 8  # sublane round-up
    gb = 0.0
    fl = 0.0
    for side in ("user", "item"):
        rows = plan[f"rows_{side}"]
        if plan.get("mode") == "fused":
            # transposed gather write+read of cv_t [nt, k_pad, T] + wrv
            # [nt, 8, T] read + seg3 + one output write per block
            # (VMEM-carried: no accumulator re-reads)
            gb += rows * (2 * k_pad * 4 + 8 * 4 + 4) / 1e9
            gb += plan[f"blocks_{side}"] * 128 * row_b / 1e9
        else:
            # gather factors + write flat rows + kernel read
            gb += rows * (512 + 2 * row_b) / 1e9
            # per-chunk accumulator read-modify-write
            gb += (
                plan[f"chunks_{side}"] * plan[f"blocks_{side}"] * 128
                * row_b * 3
            ) / 1e9
        fl += 2.0 * rows * 128 * width * passes / 1e12
    return {"gb_per_iter": gb, "tflop_eq_per_iter": fl}


# ---------------------------------------------------------------------------
# bench schema + perf-regression gate

#: BENCH json schema: v2 introduced the roofline/utilization fields and the
#: compare gate; v3 adds the ``--devices N`` sharded section (flat
#: ``sharded_*`` metrics + the ``sharded_devices`` config echo the gate
#: refuses to cross-compare); v4 adds the ``--fleet N`` router section
#: (``fleet_*`` metrics + the ``fleet_replicas`` config echo, same
#: cross-compare refusal); v5 adds the solo async-dispatch e2e number
#: (``serving_solo_e2e_p50_ms`` — wall INCLUDING dispatch, the PR 12
#: target), ``factor_cache_hit_rate``, and the fused-topk roofline block;
#: v6 grows the event-store section (``--events-scale``): throughput
#: rates (``events_write_mb_s``/``events_scan_mb_s``), the per-user
#: history latency (``events_user_history_p50_ms`` — the serving-path
#: point read), and the post-compaction backlog echo
#: (``events_compaction_backlog``), plus the ``events_scale_m`` config
#: echo the gate refuses to cross-compare; v7 adds the ``cost_attribution``
#: block: per-query attributed device cost for the ALS and NCF serving
#: paths (``cost_als_device_us_per_query`` / ``cost_ncf_device_us_per_query``),
#: metering overhead (``cost_metering_overhead_pct`` — serving p50 with the
#: ledger billing vs without), and the attribution coverage fraction
#: (``cost_attribution_coverage_frac`` — attributed device-seconds over
#: measured device-seconds, 1.0 when conservation holds), plus the
#: event-visibility freshness p99 echo (``events_visibility_lag_p99_s``);
#: v8 adds the ``fleet_day`` section (``bench.py --fleet N --day``): a
#: scripted mini production day replayed through the real multi-replica
#: topology — worst-phase tail latency (``fleet_day_p99_ms``), shed and
#: retry-elsewhere rates over the whole day (``fleet_day_shed_rate`` /
#: ``fleet_day_retry_rate``), total attributed device cost
#: (``fleet_day_device_s``), the verdict booleans as diagnostics, and the
#: ``fleet_day_scenario`` config echo the gate refuses to cross-compare
#: (a calm day vs one with a mid-peak SIGKILL is not the same
#: measurement); v9 grows the ``fleet_day`` section with the two-tenant
#: isolation run (``replay.tenant_day``): the noisy-neighbor verdict
#: (``fleet_day_tenant_isolation_pass``), the innocent tenant's
#: availability under a neighbor's 10× quota flood
#: (``fleet_day_tenant_victim_availability``) and its tail latency
#: (``fleet_day_tenant_victim_p99_ms``).  ``pio bench --compare``
#: refuses version-less or older files.
BENCH_SCHEMA_VERSION = 9

#: regression-gateable BENCH metrics and which direction is better.  Only
#: keys present in BOTH files are compared; everything else (configuration
#: echoes, section diagnostics) is ignored by the gate.
BENCH_GATE_METRICS: dict[str, str] = {
    # headline + latency: lower is better
    "value": "lower",
    "train_cold_s": "lower",
    "als_rank32_iter_s": "lower",
    "serving_p50_ms": "lower",
    "serving_p50_concurrent32_ms": "lower",
    "serving_p99_concurrent32_ms": "lower",
    # solo end-to-end WALL including dispatch through the pipelined async
    # path — the number the ~100 ms tunnel RTT used to hide behind
    "serving_solo_e2e_p50_ms": "lower",
    "ncf_serving_p50_ms": "lower",
    "ncf_solo_device_ms": "lower",
    "ncf_wave32_pipelined_ms": "lower",
    "ncf_pretrain_s": "lower",
    "events20m_write_s": "lower",
    "events20m_scan_s": "lower",
    # event-store data plane (schema v6): throughput up, serving-path
    # history reads down, post-compaction backlog down
    "events_write_mb_s": "higher",
    "events_scan_mb_s": "higher",
    "events_user_history_p50_ms": "lower",
    "events_compaction_backlog": "lower",
    # throughput / quality / roofline: higher is better
    "vs_baseline": "higher",
    "map_at_10": "higher",
    "precision_at_10": "higher",
    "ncf_map_at_10": "higher",
    "ncf_precision_at_10": "higher",
    "ncf_epochs_per_s": "higher",
    "roofline_achieved_gb_s": "higher",
    "roofline_achieved_tflop_s": "higher",
    # repeat-entity factor-cache effectiveness + fused-topk roofline
    "factor_cache_hit_rate": "higher",
    "fused_topk_achieved_gb_s": "higher",
    "fused_topk_hbm_utilization_frac": "higher",
    # sharded section (bench --devices N): lower is better
    "sharded_train_s": "lower",
    "sharded_serving_p50_ms": "lower",
    "sharded_serving_p99_ms": "lower",
    # fleet section (bench --fleet N): the router hop must stay cheap
    "fleet_router_p50_ms": "lower",
    "fleet_router_p99_ms": "lower",
    "fleet_router_overhead_ms": "lower",
    # cost-attribution section (schema v7): the metering tax must stay
    # negligible, attribution must stay conservative (coverage ~1.0), and
    # the freshness signal must not quietly decay
    "cost_metering_overhead_pct": "lower",
    "cost_attribution_coverage_frac": "higher",
    "events_visibility_lag_p99_s": "lower",
    # production-day section (schema v8, bench --fleet N --day): the whole
    # scripted day must not get slower, sheddier, retry-happier or more
    # expensive release over release
    "fleet_day_p99_ms": "lower",
    "fleet_day_shed_rate": "lower",
    "fleet_day_retry_rate": "lower",
    "fleet_day_device_s": "lower",
    # two-tenant isolation run (schema v9): an innocent neighbor's
    # availability and tail under a co-tenant's quota flood must not decay
    "fleet_day_tenant_victim_availability": "higher",
    "fleet_day_tenant_victim_p99_ms": "lower",
}


def compare_bench(
    current: Mapping[str, Any],
    previous: Mapping[str, Any],
    tolerance_pct: float = 10.0,
) -> tuple[int, dict[str, Any]]:
    """The ``pio bench --compare`` gate: exit-code, report.

    0 = no gateable metric regressed beyond ``tolerance_pct``;
    1 = at least one did (the CI gate trips);
    2 = either file is missing ``schema_version`` or carries an old one —
    version-less BENCH lines predate the gate and must not silently pass.
    """
    report: dict[str, Any] = {
        "tolerance_pct": tolerance_pct,
        "schema_version": BENCH_SCHEMA_VERSION,
        "checked": 0,
        "regressions": [],
        "improvements": [],
    }
    for name, d in (("current", current), ("previous", previous)):
        sv = d.get("schema_version")
        if sv != BENCH_SCHEMA_VERSION:
            report["error"] = (
                f"{name} bench json has schema_version={sv!r}; this gate "
                f"needs {BENCH_SCHEMA_VERSION} (re-run bench.py to produce "
                "a comparable line)"
            )
            return 2, report
    # the headline "metric" key encodes the run configuration (scale
    # suffix): gating a full-scale run against a scale-0.1 file would
    # produce a confident 10x "regression" — refuse instead
    cur_metric, prev_metric = current.get("metric"), previous.get("metric")
    if cur_metric != prev_metric:
        report["error"] = (
            f"bench configurations differ: current metric={cur_metric!r} "
            f"vs previous {prev_metric!r} — these runs are not comparable"
        )
        return 2, report
    # sharded-section config: an 8-device sharded run gated against a
    # 2-device file would "regress" by construction — refuse, like the
    # scale-suffix check above (absent-on-both means no sharded section ran)
    cur_dev = current.get("sharded_devices")
    prev_dev = previous.get("sharded_devices")
    if cur_dev != prev_dev:
        report["error"] = (
            f"sharded sections differ: current sharded_devices={cur_dev!r} "
            f"vs previous {prev_dev!r} — re-run bench with the same "
            "--devices to compare"
        )
        return 2, report
    # fleet-section config: router latency over 2 replicas vs 8 is not the
    # same measurement — refuse mismatched --fleet runs like --devices
    cur_fleet = current.get("fleet_replicas")
    prev_fleet = previous.get("fleet_replicas")
    if cur_fleet != prev_fleet:
        report["error"] = (
            f"fleet sections differ: current fleet_replicas={cur_fleet!r} "
            f"vs previous {prev_fleet!r} — re-run bench with the same "
            "--fleet to compare"
        )
        return 2, report
    # production-day section config: fleet_day_* numbers only compare when
    # the scripted day was the same script — a calm day vs one with a
    # mid-peak SIGKILL "regresses" by construction
    cur_day = current.get("fleet_day_scenario")
    prev_day = previous.get("fleet_day_scenario")
    if cur_day != prev_day:
        report["error"] = (
            f"production-day sections differ: current fleet_day_scenario="
            f"{cur_day!r} vs previous {prev_day!r} — re-run bench with the "
            "same --day scenario to compare"
        )
        return 2, report
    # event-store section config: a 100M-row write rate vs a 20M one is
    # not the same measurement — refuse mismatched --events-scale runs
    cur_ev = current.get("events_scale_m")
    prev_ev = previous.get("events_scale_m")
    if cur_ev != prev_ev:
        report["error"] = (
            f"event-store sections differ: current events_scale_m="
            f"{cur_ev!r} vs previous {prev_ev!r} — re-run bench with the "
            "same --events-scale to compare"
        )
        return 2, report
    for key in sorted(BENCH_GATE_METRICS):
        direction = BENCH_GATE_METRICS[key]
        prev, cur = previous.get(key), current.get(key)
        if (
            not isinstance(prev, (int, float))
            or not isinstance(cur, (int, float))
            or isinstance(prev, bool)
            or isinstance(cur, bool)
            or prev == 0
        ):
            continue
        change_pct = (cur - prev) / abs(prev) * 100.0
        worse = change_pct > 0 if direction == "lower" else change_pct < 0
        entry = {
            "metric": key,
            "previous": prev,
            "current": cur,
            "change_pct": round(change_pct, 3),
            "better": direction,
        }
        report["checked"] += 1
        if abs(change_pct) <= tolerance_pct:
            continue
        (report["regressions"] if worse else report["improvements"]).append(
            entry
        )
    return (1 if report["regressions"] else 0), report


# ---------------------------------------------------------------------------
# straggler & imbalance detection


class StragglerBoard:
    """Per-wave shard-time skew tracking and straggler attribution.

    Every sharded wave reports its per-device settle seconds
    (``placement.run_observed_wave`` measures them shard by shard); the
    board computes the wave's **skew fraction** — ``max / median - 1`` over
    the participating devices, 0.0 for a perfectly balanced wave — into
    ``pio_shard_skew_frac{fn}``, keeps a rolling per-device scoreboard
    (waves participated, waves slowest, cumulative seconds), and flags a
    **straggler** when ONE device is the slowest with skew above
    ``skew_threshold`` for ``patience`` consecutive waves (a single slow
    wave is noise; the same device dragging every wave is a sick chip, a
    co-tenant, or an imbalanced placement).  Byte imbalance
    (``max / mean - 1`` over per-device bytes, from ``shard_attribution``)
    rides along as ``pio_shard_bytes_imbalance_frac{fn}``.

    Thresholds come from ``PIO_SHARD_SKEW_THRESHOLD`` (default 0.5: the
    slowest shard runs 1.5x the median) and ``PIO_SHARD_SKEW_PATIENCE``
    (default 3 consecutive waves).  ``snapshot`` is the ``/shards.json``
    scoreboard body.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        skew_threshold: float | None = None,
        patience: int | None = None,
    ):
        if skew_threshold is None:
            try:
                skew_threshold = float(
                    os.environ.get("PIO_SHARD_SKEW_THRESHOLD", "0.5")
                )
            except ValueError:
                skew_threshold = 0.5
        if patience is None:
            try:
                patience = int(os.environ.get("PIO_SHARD_SKEW_PATIENCE", "3"))
            except ValueError:
                patience = 3
        self.skew_threshold = skew_threshold
        self.patience = max(patience, 1)
        self._lock = threading.Lock()
        #: fn -> scoreboard state (all mutation under _lock)
        self._fns: dict[str, dict[str, Any]] = {}
        reg = registry or REGISTRY
        self._g_skew = reg.gauge(
            "pio_shard_skew_frac",
            "Last sharded wave's max/median shard-time skew (0 = balanced)",
            labelnames=("fn",),
        )
        self._g_bytes_imbalance = reg.gauge(
            "pio_shard_bytes_imbalance_frac",
            "Per-device bytes max/mean imbalance of a sharded array group",
            labelnames=("fn",),
        )
        self._c_stragglers = reg.counter(
            "pio_shard_straggler_total",
            "Straggler flags raised (one device slowest past the skew "
            "threshold for `patience` consecutive waves)",
            labelnames=("fn", "device"),
        )

    def record_wave(
        self,
        fn: str,
        shard_seconds: Mapping[str, float],
        shard_bytes: Mapping[str, float] | None = None,
    ) -> float:
        """Record one sharded wave's per-device seconds (and optionally the
        per-device byte attribution); returns the wave's skew fraction."""
        secs = {str(k): float(v) for k, v in shard_seconds.items() if v >= 0}
        if len(secs) < 2:
            return 0.0
        med = statistics.median(secs.values())
        slowest = max(secs, key=secs.get)  # type: ignore[arg-type]
        skew = (secs[slowest] / med - 1.0) if med > 0 else 0.0
        breach = skew > self.skew_threshold
        flagged = False
        with self._lock:
            entry = self._fns.setdefault(
                fn,
                {
                    "waves": 0,
                    "last_skew": 0.0,
                    "last_max_device": None,
                    "streak_device": None,
                    "streak": 0,
                    "straggler": None,
                    "devices": {},
                },
            )
            entry["waves"] += 1
            entry["last_skew"] = round(skew, 6)
            entry["last_max_device"] = slowest
            for dev, s in secs.items():
                d = entry["devices"].setdefault(
                    dev, {"waves": 0, "slowest": 0, "seconds": 0.0}
                )
                d["waves"] += 1
                d["seconds"] = round(d["seconds"] + s, 6)
            entry["devices"][slowest]["slowest"] += 1
            if breach:
                if entry["streak_device"] == slowest:
                    entry["streak"] += 1
                else:
                    entry["streak_device"] = slowest
                    entry["streak"] = 1
                if (
                    entry["streak"] >= self.patience
                    and entry["straggler"] != slowest
                ):
                    entry["straggler"] = slowest
                    flagged = True
            else:
                entry["streak_device"] = None
                entry["streak"] = 0
                entry["straggler"] = None
        self._g_skew.labels(fn).set(skew)
        if shard_bytes:
            vals = [float(v) for v in shard_bytes.values()]
            mean = sum(vals) / len(vals) if vals else 0.0
            imbalance = (max(vals) / mean - 1.0) if mean > 0 else 0.0
            self._g_bytes_imbalance.labels(fn).set(imbalance)
        if flagged:
            self._c_stragglers.labels(fn, slowest).inc()
            log.warning(
                "shard straggler: device %s is the slowest shard of %s for "
                "%d consecutive waves (skew %.0f%% over the median, "
                "threshold %.0f%%) — check chip health / co-tenancy / "
                "placement balance (/shards.json has the scoreboard)",
                slowest,
                fn,
                self.patience,
                skew * 100.0,
                self.skew_threshold * 100.0,
                extra={
                    "fn": fn,
                    "device": slowest,
                    "skew_frac": round(skew, 4),
                    "patience": self.patience,
                },
            )
        return skew

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            fns = {
                fn: {
                    **{k: v for k, v in e.items() if k != "devices"},
                    "devices": {d: dict(v) for d, v in e["devices"].items()},
                }
                for fn, e in self._fns.items()
            }
        return {
            "skew_threshold": self.skew_threshold,
            "patience": self.patience,
            "functions": fns,
        }

    def clear(self) -> None:
        with self._lock:
            self._fns.clear()


# ---------------------------------------------------------------------------
# process defaults + the /efficiency.json body

#: process-global trackers: device telemetry is per-process like the jit
#: cache and the profiler — servers with isolated registries still share
#: the one accelerator
DEVICE_EFFICIENCY = EfficiencyTracker()
RECOMPILES = RecompileTracker()
STRAGGLERS = StragglerBoard()


def default_stragglers() -> StragglerBoard:
    return STRAGGLERS


def default_efficiency() -> EfficiencyTracker:
    return DEVICE_EFFICIENCY


def default_recompiles() -> RecompileTracker:
    return RECOMPILES


def shard_snapshot(registry: MetricsRegistry | None = None) -> dict[str, Any]:
    """Per-device shard attribution as recorded by
    ``parallel.mesh.meter_shards``: ``{fn: {device: {bytes, waves,
    seconds}}}`` plus the participating-device list (the "mesh shape" an
    operator sees).  Empty when nothing sharded has run."""
    reg = registry or REGISTRY
    out: dict[str, dict[str, dict[str, float]]] = {}
    fam_bytes = reg.get("pio_shard_bytes")
    if fam_bytes is not None:
        for (fn, device), child in fam_bytes.series():
            out.setdefault(fn, {})[device] = {
                "bytes": float(getattr(child, "value", 0.0))
            }
    fam_secs = reg.get("pio_shard_seconds")
    if fam_secs is not None:
        for (fn, device), child in fam_secs.series():
            entry = out.setdefault(fn, {}).setdefault(device, {})
            entry["waves"] = int(getattr(child, "count", 0))
            entry["seconds"] = round(float(getattr(child, "sum", 0.0)), 6)
    devices = sorted({d for per_fn in out.values() for d in per_fn})
    return {"devices": devices, "functions": out}


def shards_snapshot(
    registry: MetricsRegistry | None = None,
    stragglers: StragglerBoard | None = None,
) -> dict[str, Any]:
    """The ``GET /shards.json`` body: per-device placement attribution
    (bytes/waves/seconds per fn) plus the rolling straggler scoreboard —
    the one scrape that answers "which device is dragging the mesh"."""
    return {
        "shards": shard_snapshot(registry),
        "stragglers": (stragglers or STRAGGLERS).snapshot(),
    }


def device_snapshot(
    efficiency: EfficiencyTracker | None = None,
    recompiles: RecompileTracker | None = None,
) -> dict[str, Any]:
    """The ``GET /efficiency.json`` body: achieved-vs-peak per entry point,
    recompile accounting (with any active storm), transfer tallies, and the
    per-device shard attribution of any sharded model."""
    snap = (efficiency or DEVICE_EFFICIENCY).snapshot()
    snap["recompiles"] = (recompiles or RECOMPILES).snapshot()
    snap["transfers"] = {
        f"{k}_bytes": v for k, v in transfer_totals().items()
    }
    snap["shards"] = shard_snapshot()
    return snap


#: buckets for the per-stage wave histograms — reuse the stage range
WAVE_STAGE_BUCKETS = STAGE_BUCKETS
