"""Observability: metrics, tracing, structured logs, flight recorder, SLO.

The subsystem every later perf PR leans on — counters/gauges/log-bucketed
histograms (metrics.py), context-manager spans with a recent-trace ring
(tracing.py), request-id-correlated JSON-lines logging with an in-process
ring (logging.py), a flight recorder for the slowest/errored requests
(flight.py), rolling-window SLO tracking with burn rates + health routes
(slo.py), on-demand jax.profiler capture (profiler.py), online model-quality
monitoring — prediction log, feedback joins, drift detection (quality.py) —
device-efficiency attribution — XLA cost/roofline capture, recompile-storm
detection, wave-timeline splits, the bench perf-regression gate (device.py)
— HTTP exposition for all of it (http.py), a sniffer plugin proving the
plugin seams can consume the registry (plugin.py), and the watch loop that
turns it all into autonomous detection: a declarative alert rules engine
(alerts.py) whose firing transitions snapshot forensic incident bundles to
disk before the bounded rings rotate the evidence away (incident.py).
Dependency-free; the process-global default registry is ``REGISTRY``.
"""

from predictionio_tpu.obs.alerts import (
    AlertEvaluator,
    AlertRule,
    default_rule_pack,
    resolve_rules,
)
from predictionio_tpu.obs.costs import (
    CostLedger,
    RequestCost,
    current_cost,
    default_ledger,
    note_storage_read,
    request_cost,
)
from predictionio_tpu.obs.device import (
    DEVICE_EFFICIENCY,
    RECOMPILES,
    DevicePeaks,
    EfficiencyTracker,
    RecompileTracker,
    compare_bench,
    device_peaks,
    device_snapshot,
    jit_cost_analysis,
    wave_stage,
    wave_timeline,
)
from predictionio_tpu.obs.flight import FLIGHT, FlightRecorder, annotate
from predictionio_tpu.obs.incident import IncidentRecorder, load_bundle
from predictionio_tpu.obs.logging import (
    REQUEST_ID_HEADER,
    JsonLineFormatter,
    LogRing,
    configure_logging,
    get_log_ring,
    get_request_id,
    new_request_id,
    reset_request_context,
    set_request_context,
)
from predictionio_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    STAGE_BUCKETS,
    TRAIN_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsHistory,
    MetricsRegistry,
    default_registry,
    quantile_from_buckets,
)
from predictionio_tpu.obs.profiler import PROFILER, sample_runtime_gauges
from predictionio_tpu.obs.quality import (
    DriftDetector,
    HistogramSketch,
    QualityMonitor,
    default_quality,
)
from predictionio_tpu.obs.slo import SLOTracker
from predictionio_tpu.obs.tracing import (
    Span,
    clear_traces,
    current_span,
    install_jax_compile_listener,
    observe_span,
    recent_traces,
    trace,
)

__all__ = [
    "AlertEvaluator",
    "AlertRule",
    "DEVICE_EFFICIENCY",
    "DevicePeaks",
    "EfficiencyTracker",
    "FLIGHT",
    "FlightRecorder",
    "IncidentRecorder",
    "JsonLineFormatter",
    "LATENCY_BUCKETS",
    "LogRing",
    "PROFILER",
    "REGISTRY",
    "REQUEST_ID_HEADER",
    "SIZE_BUCKETS",
    "SLOTracker",
    "STAGE_BUCKETS",
    "TRAIN_BUCKETS",
    "CostLedger",
    "Counter",
    "DriftDetector",
    "Gauge",
    "Histogram",
    "HistogramSketch",
    "MetricsHistory",
    "MetricsRegistry",
    "QualityMonitor",
    "RECOMPILES",
    "RecompileTracker",
    "RequestCost",
    "Span",
    "annotate",
    "clear_traces",
    "compare_bench",
    "configure_logging",
    "current_cost",
    "current_span",
    "default_ledger",
    "default_quality",
    "default_registry",
    "default_rule_pack",
    "load_bundle",
    "resolve_rules",
    "device_peaks",
    "device_snapshot",
    "jit_cost_analysis",
    "get_log_ring",
    "get_request_id",
    "install_jax_compile_listener",
    "new_request_id",
    "note_storage_read",
    "observe_span",
    "request_cost",
    "quantile_from_buckets",
    "recent_traces",
    "reset_request_context",
    "sample_runtime_gauges",
    "set_request_context",
    "trace",
    "wave_stage",
    "wave_timeline",
]
