"""Observability: metrics registry, per-stage tracing, exposition.

The subsystem every later perf PR leans on — counters/gauges/log-bucketed
histograms (metrics.py), context-manager spans with a recent-trace ring
(tracing.py), Prometheus + JSON HTTP exposition (http.py), and a sniffer
plugin proving the plugin seams can consume the registry (plugin.py).
Dependency-free; the process-global default registry is ``REGISTRY``.
"""

from predictionio_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    REGISTRY,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    quantile_from_buckets,
)
from predictionio_tpu.obs.tracing import (
    Span,
    clear_traces,
    current_span,
    install_jax_compile_listener,
    observe_span,
    recent_traces,
    trace,
)

__all__ = [
    "LATENCY_BUCKETS",
    "REGISTRY",
    "SIZE_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "clear_traces",
    "current_span",
    "default_registry",
    "install_jax_compile_listener",
    "observe_span",
    "quantile_from_buckets",
    "recent_traces",
    "trace",
]
