"""Lock-contention attribution for the process's hot locks.

A degraded coalescing rate or a lengthened wave tail often traces back to a
host lock: the MicroBatcher condition, the metrics registry, the quality
monitor's prediction-log lock.  Until now that was a hunch reconstructed
from span gaps (the PR 9 span-id finding class); these wrappers turn it
into a gauge.

:class:`ContendedLock` wraps a ``threading.Lock`` (or ``RLock`` with
``reentrant=True``) and meters ONLY the contended path: an uncontended
acquisition is one non-blocking ``acquire(False)`` attempt — no clock
reads, no metric writes — so adopting the wrapper costs the hot path
nothing when the lock is free.  When the fast path loses, the blocking
acquisition is timed into ``pio_lock_wait_seconds{lock}`` and counted in
``pio_lock_contended_total{lock}``.

:class:`ContendedCondition` is a ``threading.Condition`` built over a
:class:`ContendedLock`, so condition re-acquisition after ``wait()`` —
where waiters pile up behind the notifier — is attributed too.

Metric children resolve lazily on first contention (never at import), and
a thread-local re-entrancy guard lets the metrics registry instrument its
OWN lock: resolving the lock metrics walks the registry, which acquires
the registry lock; a resolution already in flight on this thread skips the
observation instead of deadlocking on itself.

:class:`LockWitness` is the runtime half of the static lock-order analysis
(``analysis/callgraph.py`` + PIO-LOCK001): with ``PIO_LOCK_WITNESS=1`` (or
:func:`enable_witness`), every ContendedLock acquisition records the
per-thread held-lock stack, accumulates the executed "held A, acquired B"
edge set, and flags order inversions *actually run* — counted in
``pio_lock_order_violations_total{pair}`` and dumped (with the edge set)
at the debug-gated ``/locks.json`` route.  A tier-1 test asserts the
witnessed edge set is a subgraph of the static acquisition graph.  With
the witness off (the default) the only cost on the uncontended fast path
is one module-global load and a None check.
"""

from __future__ import annotations

import os
import threading
import time

#: re-entrancy guard: True while THIS thread is resolving lock metrics
#: through the registry (whose own lock may be a ContendedLock)
_resolving = threading.local()

#: cap on retained violation records (the counter keeps exact totals)
_WITNESS_MAX_VIOLATIONS = 100


class LockWitness:
    """Runtime lock-order recorder for ContendedLock acquisitions.

    Per-thread held-name stacks live in a ``threading.local``; the shared
    edge table is guarded by a plain ``threading.Lock`` (the witness must
    not instrument itself).  An inversion is recorded the moment an edge
    ``(B, A)`` is executed while ``(A, B)`` was ever executed before — the
    interleaving that deadlocks did not need to happen, only both orders.

    Acquisitions made while this thread is resolving metric children
    (``_resolving.busy``) are invisible: those are the instrumentation's
    own registry walks, not application lock nesting.
    """

    def __init__(self) -> None:
        self._tls = threading.local()
        self._mu = threading.Lock()
        self._edges: dict[tuple[str, str], int] = {}
        self._violations: list[dict] = []

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquired(self, name: str) -> None:
        if getattr(_resolving, "busy", False):
            return
        held = self._held()
        if name in held:
            held.append(name)  # re-entrant: no new ordering fact
            return
        inversions: list[tuple[str, str]] = []
        if held:
            with self._mu:
                for h in dict.fromkeys(held):
                    pair = (h, name)
                    self._edges[pair] = self._edges.get(pair, 0) + 1
                    if (name, h) in self._edges:
                        inversions.append(pair)
                        if len(self._violations) < _WITNESS_MAX_VIOLATIONS:
                            self._violations.append(
                                {
                                    "pair": "|".join(sorted((h, name))),
                                    "held": h,
                                    "acquired": name,
                                    "stack": list(held) + [name],
                                    "thread": threading.current_thread().name,
                                }
                            )
        held.append(name)
        for pair in inversions:
            self._count_violation(pair)

    def note_released(self, name: str) -> None:
        if getattr(_resolving, "busy", False):
            return
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    def _count_violation(self, pair: tuple[str, str]) -> None:
        """Bump the violations counter OUTSIDE the witness mutex, with the
        metrics-resolution guard set so the registry walk (which acquires
        the registry's own ContendedLock) is not witnessed as more edges."""
        if getattr(_resolving, "busy", False):
            return
        _resolving.busy = True
        try:
            from predictionio_tpu.obs.metrics import REGISTRY

            REGISTRY.counter(
                "pio_lock_order_violations_total",
                "Runtime lock-order inversions observed by the LockWitness",
                labelnames=("pair",),
            ).labels("|".join(sorted(pair))).inc()
        except Exception:
            pass  # telemetry must never take the serving path down
        finally:
            _resolving.busy = False

    def snapshot(self) -> dict:
        """Edge set + retained violations (the /locks.json payload)."""
        with self._mu:
            edges = sorted(self._edges.items())
            violations = list(self._violations)
        return {
            "enabled": True,
            "edges": [
                {"src": a, "dst": b, "count": n} for (a, b), n in edges
            ],
            "violations": violations,
        }

    def edge_set(self) -> set:
        with self._mu:
            return set(self._edges)

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()


#: process witness; installed at import when PIO_LOCK_WITNESS=1, or later
#: via enable_witness() (tests).  Read once per acquisition — keep it a
#: single module-global load.
_WITNESS: LockWitness | None = (
    LockWitness() if os.environ.get("PIO_LOCK_WITNESS") == "1" else None
)


def witness() -> LockWitness | None:
    return _WITNESS


def enable_witness() -> LockWitness:
    global _WITNESS
    _WITNESS = LockWitness()
    return _WITNESS


def disable_witness() -> None:
    global _WITNESS
    _WITNESS = None


def witness_snapshot() -> dict:
    w = _WITNESS
    if w is None:
        return {"enabled": False, "edges": [], "violations": []}
    return w.snapshot()


class ContendedLock:
    """A ``with``-able lock whose blocked acquisitions are metered.

    ``reentrant=True`` wraps an ``RLock`` (a re-entrant acquisition by the
    owning thread takes the uncontended fast path, as it should — the
    thread never blocks).  ``registry`` defaults to the process registry,
    resolved lazily so construction order never matters.
    """

    __slots__ = ("name", "_inner", "_registry", "_m_wait", "_m_contended")

    def __init__(
        self,
        name: str,
        registry=None,
        reentrant: bool = False,
    ):
        self.name = name
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._registry = registry
        self._m_wait = None
        self._m_contended = None

    def prime(self) -> "ContendedLock":
        """Resolve the metric children NOW, while the caller guarantees
        nothing holds the lock.  Required for a registry instrumenting its
        OWN lock: a lazy resolution inside a contended acquire would walk
        the registry and re-acquire the very lock being reported on —
        self-deadlock on a non-reentrant lock."""
        self._metrics()
        return self

    def _metrics(self):
        """(wait histogram, contended counter) children, or (None, None)
        while a resolution through the registry is already in flight on
        this thread (the registry's own lock instrumenting itself)."""
        if self._m_wait is not None:
            return self._m_wait, self._m_contended
        if getattr(_resolving, "busy", False):
            return None, None
        _resolving.busy = True
        try:
            reg = self._registry
            if reg is None:
                # lazy, and ONLY on the default path: the process registry
                # instruments its own lock with registry=self, and resolves
                # while obs.metrics is still mid-import
                from predictionio_tpu.obs.metrics import REGISTRY

                reg = REGISTRY
            m_wait = reg.histogram(
                "pio_lock_wait_seconds",
                "Time spent blocked acquiring an instrumented hot lock",
                labelnames=("lock",),
            ).labels(self.name)
            # the counter resolves (and publishes) BEFORE the histogram:
            # the early return above keys on _m_wait, so a concurrent
            # caller observing it set must never see _m_contended None —
            # acquire() would .inc() on None with the inner lock held
            self._m_contended = reg.counter(
                "pio_lock_contended_total",
                "Acquisitions of an instrumented hot lock that had to block",
                labelnames=("lock",),
            ).labels(self.name)
            self._m_wait = m_wait
        finally:
            _resolving.busy = False
        return self._m_wait, self._m_contended

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # uncontended fast path: one non-blocking attempt, zero telemetry —
        # histogram mass appears ONLY when an acquisition genuinely blocked
        # (witness off: the only overhead here is one global load + is-None)
        if self._inner.acquire(False):
            w = _WITNESS
            if w is not None:
                w.note_acquired(self.name)
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        ok = self._inner.acquire(True, timeout)
        wait_s = time.perf_counter() - t0
        m_wait, m_contended = self._metrics()
        if m_wait is not None:
            m_contended.inc()
            m_wait.observe(wait_s)
        if ok:
            w = _WITNESS
            if w is not None:
                w.note_acquired(self.name)
        return ok

    def release(self) -> None:
        w = _WITNESS
        if w is not None:
            w.note_released(self.name)
        self._inner.release()

    def __enter__(self) -> "ContendedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        w = _WITNESS
        if w is not None:
            w.note_released(self.name)
        self._inner.release()


class ContendedCondition:
    """``threading.Condition`` over a :class:`ContendedLock`.

    Drop-in for the stdlib Condition surface the servers use (``with``,
    ``wait``, ``wait_for``, ``notify``, ``notify_all``); every blocked
    acquisition — including the re-acquisition inside ``wait`` — lands in
    the lock's wait histogram.
    """

    __slots__ = ("lock", "_cond")

    def __init__(self, name: str, registry=None):
        self.lock = ContendedLock(name, registry=registry)
        self._cond = threading.Condition(self.lock)

    def __enter__(self):
        self._cond.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        return self._cond.__exit__(exc_type, exc, tb)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self.lock.acquire(blocking, timeout)

    def release(self) -> None:
        self.lock.release()

    def wait(self, timeout: float | None = None) -> bool:
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()
