"""Dependency-free metrics registry with Prometheus text exposition.

The telemetry backbone the reference never had (its only instrument is the
event server's hourly StatsActor): thread-safe ``Counter`` / ``Gauge`` /
``Histogram`` families keyed by label values, collected in a
``MetricsRegistry`` and rendered either as Prometheus text format
(``GET /metrics``) or JSON (``GET /metrics.json``).

Histograms are log-bucketed over FIXED boundaries (``LATENCY_BUCKETS``,
10 µs – 10 s, four buckets per decade) so two histograms — or the same
histogram sampled at two moments — merge by elementwise addition with no
allocation or boundary negotiation.  Size-shaped quantities (batch sizes,
queue depths) use the power-of-two ``SIZE_BUCKETS``; a family's buckets are
fixed at creation so every child shares them.

The hot-path cost of ``observe``/``inc`` is one ``bisect`` plus one lock
acquire (sub-microsecond on CPython); serving instrumentation budget is
<5 µs/query and tests assert a loose 50 µs bound.
"""

from __future__ import annotations

import json
import math
import threading
from bisect import bisect_left
from collections import deque
from typing import Any, Iterable, Mapping

from predictionio_tpu.obs.contention import ContendedLock

#: Fixed log-spaced bucket upper bounds in seconds: 10 µs .. 10 s, four per
#: decade.  Shared by every latency histogram so merging is allocation-free.
LATENCY_BUCKETS: tuple[float, ...] = tuple(
    round(10.0 ** (e + f / 4.0), 12) for e in range(-5, 1) for f in range(4)
) + (10.0,)

#: Power-of-two bounds for size-shaped histograms (batch size, queue depth).
SIZE_BUCKETS: tuple[float, ...] = tuple(float(2**i) for i in range(13))

#: Coarser bounds for second-to-hour-scale stages (XLA compiles, long batch
#: jobs): 1 ms – 10 000 s, two buckets per decade.  The serving-latency set
#: tops out at 10 s, which would clamp train-stage quantiles.
STAGE_BUCKETS: tuple[float, ...] = tuple(
    round(10.0 ** (e + f / 2.0), 9) for e in range(-3, 4) for f in range(2)
) + (10000.0,)

#: Train/eval span bounds: 100 µs – 600 s.  Bucket bounds are configurable
#: per histogram family (``buckets=``); this is the set ``pio_span_seconds``
#: uses, chosen so sub-millisecond eval folds AND 40 s+ train/event-store
#: stages (BENCH_r05) both keep meaningful quantiles — a range that tops out
#: at 10 s silently pins a 40 s stage's p99 to 10 s.
TRAIN_BUCKETS: tuple[float, ...] = tuple(
    round(10.0 ** (e + f / 2.0), 9) for e in range(-4, 3) for f in range(2)
) + (600.0,)


def _fmt(v: float) -> str:
    """Prometheus sample value / ``le`` formatting ('+Inf', trim zeros)."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_text(names: tuple[str, ...], values: tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value that can go up and down."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative log-bucketed histogram over fixed bounds.

    ``counts[i]`` counts observations ``<= bounds[i]``; the final slot is
    the +Inf bucket.  All mutation happens under one lock; ``merge_counts``
    on two snapshots is plain elementwise addition because bounds are fixed
    per family.
    """

    __slots__ = ("_lock", "bounds", "_counts", "_sum", "_count")

    def __init__(self, bounds: tuple[float, ...] = LATENCY_BUCKETS):
        self._lock = threading.Lock()
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1

    def observe_many(self, value: float, n: int) -> None:
        """Record ``n`` identical observations with one bucket update.

        Used by row-weighted observers (e.g. visibility lag weighted by
        segment row count) where per-row ``observe`` calls would be O(rows).
        """
        if n <= 0:
            return
        idx = bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += n
            self._sum += value * n
            self._count += n

    def snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket counts, sum, count) — consistent under the lock."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper-bound linear
        interpolation within the winning bucket; +Inf bucket reports the
        largest finite bound)."""
        counts, _, total = self.snapshot()
        return quantile_from_buckets(self.bounds, counts, total, q)


def quantile_from_buckets(
    bounds: Iterable[float], counts: list[int], total: int, q: float
) -> float:
    """Shared bucket→quantile math (also used by bench.py snapshots)."""
    bounds = list(bounds)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo = bounds[i - 1] if 0 < i <= len(bounds) else 0.0
        hi = bounds[i] if i < len(bounds) else bounds[-1]
        if seen + c >= rank:
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return bounds[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """One named metric with a fixed label schema and per-label children."""

    def __init__(
        self,
        kind: str,
        name: str,
        help: str,
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ):
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self.buckets = buckets
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def labels(self, *values: Any) -> Any:
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {key}"
            )
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = (
                        Histogram(self.buckets)
                        if self.kind == "histogram"
                        else _KINDS[self.kind]()
                    )
                    self._children[key] = child
        return child

    def series(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


def history_depth_from_env(default: int = 60) -> int:
    """``PIO_METRICS_HISTORY_DEPTH`` (default 60) — how many scrape-cadence
    samples each series ring retains.  Deeper rings buy longer sparkline /
    incident-bundle trends at ``depth × series-cardinality`` floats of
    memory; a malformed value falls back to the default rather than
    killing server startup over a typo."""
    import os

    raw = os.environ.get("PIO_METRICS_HISTORY_DEPTH")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class MetricsHistory:
    """Bounded per-series history ring, sampled on scrape.

    One fixed-depth deque per (family name, label values): counters and
    gauges record their value, histograms their p95 — enough for the
    dashboard sparklines (model quality, serving latency) without a
    time-series backend.  ``sample`` is called by the ``/metrics``(.json)
    scrape handlers and by the dashboard render, so the ring advances at
    scrape cadence and memory stays ``depth × series-cardinality`` (series
    cardinality is already bounded upstream by the label guards).  Depth
    comes from ``PIO_METRICS_HISTORY_DEPTH`` unless passed explicitly; the
    rings are folded into incident bundles (obs/incident.py) so a
    post-mortem sees the pre-incident trend, not just the moment of death.
    """

    def __init__(self, depth: int | None = None):
        if depth is None:
            depth = history_depth_from_env()
        self.depth = max(depth, 2)
        self._lock = threading.Lock()
        self._series: dict[tuple[str, tuple[str, ...]], deque[float]] = {}

    def sample(self, registry: "MetricsRegistry") -> None:
        for fam in registry.families():
            for lv, child in fam.series():
                if fam.kind == "histogram":
                    counts, _, count = child.snapshot()
                    value = quantile_from_buckets(
                        fam.buckets, counts, count, 0.95
                    )
                else:
                    value = child.value
                key = (fam.name, lv)
                with self._lock:
                    dq = self._series.get(key)
                    if dq is None:
                        dq = self._series[key] = deque(maxlen=self.depth)
                    dq.append(float(value))

    def series(
        self, name: str, labels: tuple[str, ...] = ()
    ) -> list[float]:
        """Sampled values for one series, oldest first."""
        with self._lock:
            dq = self._series.get((name, tuple(labels)))
            return list(dq) if dq else []

    def items(self, name: str) -> list[tuple[tuple[str, ...], list[float]]]:
        """Every sampled series of one family: (label values, history)."""
        with self._lock:
            return sorted(
                (lv, list(dq))
                for (n, lv), dq in self._series.items()
                if n == name
            )

    def snapshot(self) -> dict[str, Any]:
        """Every ring, JSON-shaped — the incident bundle's ``history``
        section (oldest sample first per series)."""
        with self._lock:
            items = sorted(
                (name, lv, list(dq))
                for (name, lv), dq in self._series.items()
            )
        out: dict[str, Any] = {"depth": self.depth, "series": {}}
        for name, lv, values in items:
            out["series"].setdefault(name, []).append(
                {"labels": list(lv), "values": values}
            )
        return out


class MetricsRegistry:
    """Thread-safe name → :class:`MetricFamily` registry.

    Re-declaring a family with the same (kind, labelnames) returns the
    existing one, so instrumentation points can declare their metrics at
    call-site construction time without coordinating module import order.
    Each registry owns a :class:`MetricsHistory` (``.history``) fed on every
    scrape — the sparkline backing store.
    """

    def __init__(self):
        # every call-site family lookup (incl. one per finished span)
        # funnels through this lock, so its blocked acquisitions are
        # metered; prime() resolves the lock's own metric children while
        # nothing can hold it yet — lazy resolution inside a contended
        # acquire would re-enter this registry under its own lock
        self._lock = ContendedLock("metrics_registry", registry=self)
        self._families: dict[str, MetricFamily] = {}
        self.history = MetricsHistory()
        self._lock.prime()

    def _family(
        self,
        kind: str,
        name: str,
        help: str,
        labelnames: tuple[str, ...],
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, not {kind}{labelnames}"
                    )
                if kind == "histogram" and fam.buckets != buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"different buckets"
                    )
                return fam
            fam = MetricFamily(kind, name, help, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(
        self, name: str, help: str = "", labelnames: tuple[str, ...] = ()
    ):
        fam = self._family("counter", name, help, tuple(labelnames))
        return fam if fam.labelnames else fam.labels()

    def gauge(
        self, name: str, help: str = "", labelnames: tuple[str, ...] = ()
    ):
        fam = self._family("gauge", name, help, tuple(labelnames))
        return fam if fam.labelnames else fam.labels()

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ):
        fam = self._family(
            "histogram", name, help, tuple(labelnames), tuple(buckets)
        )
        return fam if fam.labelnames else fam.labels()

    def get(self, name: str) -> MetricFamily | None:
        with self._lock:
            return self._families.get(name)

    def families(self) -> list[MetricFamily]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition ----------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text format 0.0.4."""
        out: list[str] = []
        for fam in self.families():
            out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for lv, child in fam.series():
                base = _labels_text(fam.labelnames, lv)
                if fam.kind in ("counter", "gauge"):
                    out.append(f"{fam.name}{base} {_fmt(child.value)}")
                    continue
                counts, total_sum, count = child.snapshot()
                cum = 0
                for bound, c in zip(
                    list(fam.buckets) + [math.inf], counts
                ):
                    cum += c
                    le = _labels_text(
                        fam.labelnames + ("le",), lv + (_fmt(bound),)
                    )
                    out.append(f"{fam.name}_bucket{le} {cum}")
                out.append(f"{fam.name}_sum{base} {repr(total_sum)}")
                out.append(f"{fam.name}_count{base} {count}")
        return "\n".join(out) + "\n" if out else ""

    def render_json(self) -> dict[str, Any]:
        """JSON exposition: the same data shaped for programs."""
        out: dict[str, Any] = {}
        for fam in self.families():
            series = []
            for lv, child in fam.series():
                labels = dict(zip(fam.labelnames, lv))
                if fam.kind in ("counter", "gauge"):
                    series.append({"labels": labels, "value": child.value})
                else:
                    counts, total_sum, count = child.snapshot()
                    series.append(
                        {
                            "labels": labels,
                            "count": count,
                            "sum": total_sum,
                            "buckets": counts,
                            "p50": quantile_from_buckets(
                                fam.buckets, counts, count, 0.50
                            ),
                            "p95": quantile_from_buckets(
                                fam.buckets, counts, count, 0.95
                            ),
                            "p99": quantile_from_buckets(
                                fam.buckets, counts, count, 0.99
                            ),
                        }
                    )
            out[fam.name] = {
                "type": fam.kind,
                "help": fam.help,
                "series": series,
            }
            if fam.kind == "histogram":
                out[fam.name]["bounds"] = list(fam.buckets)
        return out

    def delta_snapshot(
        self, prev: Mapping[str, Any] | None = None
    ) -> dict[str, Any]:
        """``render_json()`` minus a previous snapshot of the same registry.

        Counters subtract values; histograms subtract per-bucket counts and
        sums, then recompute p50/p95/p99 from the *delta* buckets — so a
        phase window gets true in-window quantiles without registering a
        second histogram family.  Gauges are point-in-time and pass through
        unchanged.  ``prev=None`` returns a plain absolute snapshot (the
        baseline for the next call).  Series absent from ``prev`` (born
        mid-window) subtract zero; series absent from the current snapshot
        are dropped.  See :func:`subtract_snapshots` for the pure-data form
        used on scraped ``/metrics.json`` payloads.
        """
        current = self.render_json()
        if prev is None:
            return current
        return subtract_snapshots(current, prev)

    def histogram_quantiles(
        self, name: str, qs: Iterable[float] = (0.50, 0.95, 0.99)
    ) -> dict[str, Any]:
        """Per-series quantiles for one histogram family (bench snapshots)."""
        fam = self.get(name)
        if fam is None or fam.kind != "histogram":
            return {}
        out: dict[str, Any] = {}
        for lv, child in fam.series():
            counts, _, count = child.snapshot()
            key = ",".join(f"{n}={v}" for n, v in zip(fam.labelnames, lv)) or "_"
            out[key] = {"count": count}
            for q in qs:
                out[key][f"p{int(q * 100)}"] = quantile_from_buckets(
                    fam.buckets, counts, count, q
                )
        return out


def subtract_snapshots(
    current: Mapping[str, Any], previous: Mapping[str, Any]
) -> dict[str, Any]:
    """Elementwise difference of two ``render_json()``-shaped snapshots.

    The window algebra behind per-phase verdicts: scrape once at each phase
    boundary, subtract, and the result *is* a valid snapshot of just that
    window (cumulative buckets over fixed bounds subtract cleanly — the
    reason ``LATENCY_BUCKETS`` are fixed per family).  Counter values,
    histogram bucket counts, sums, and counts subtract, clamped at zero so a
    restarted process (counter reset) degrades to "window starts at
    restart" instead of going negative; histogram quantiles are recomputed
    from the delta buckets.  Gauges keep their current value.
    """
    out: dict[str, Any] = {}
    for name, fam in current.items():
        if not isinstance(fam, Mapping) or "series" not in fam:
            continue
        prev_fam = previous.get(name)
        prev_series: dict[str, Mapping[str, Any]] = {}
        if isinstance(prev_fam, Mapping) and prev_fam.get("type") == fam.get(
            "type"
        ):
            for s in prev_fam.get("series", ()):
                prev_series[json.dumps(s.get("labels", {}), sort_keys=True)] = s
        kind = fam.get("type")
        bounds = list(fam.get("bounds", []))
        series_out = []
        for s in fam.get("series", ()):
            p = prev_series.get(
                json.dumps(s.get("labels", {}), sort_keys=True), {}
            )
            if kind == "counter":
                series_out.append(
                    {
                        "labels": dict(s.get("labels", {})),
                        "value": max(
                            float(s.get("value", 0.0))
                            - float(p.get("value", 0.0)),
                            0.0,
                        ),
                    }
                )
            elif kind == "histogram":
                cur_b = list(s.get("buckets", []))
                prev_b = list(p.get("buckets", []))
                prev_b += [0] * (len(cur_b) - len(prev_b))
                buckets = [max(c - q, 0) for c, q in zip(cur_b, prev_b)]
                count = max(int(s.get("count", 0)) - int(p.get("count", 0)), 0)
                entry: dict[str, Any] = {
                    "labels": dict(s.get("labels", {})),
                    "count": count,
                    "sum": max(
                        float(s.get("sum", 0.0)) - float(p.get("sum", 0.0)),
                        0.0,
                    ),
                    "buckets": buckets,
                }
                for q in (0.50, 0.95, 0.99):
                    entry[f"p{int(q * 100)}"] = quantile_from_buckets(
                        bounds, buckets, count, q
                    )
                series_out.append(entry)
            else:  # gauge: point-in-time, no delta semantics
                series_out.append(
                    {
                        "labels": dict(s.get("labels", {})),
                        "value": s.get("value", 0.0),
                    }
                )
        out[name] = {
            "type": kind,
            "help": fam.get("help", ""),
            "series": series_out,
        }
        if kind == "histogram":
            out[name]["bounds"] = bounds
    return out


#: Process-global default registry — what servers, the MicroBatcher, and the
#: training workflow record into unless handed an explicit registry.
REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY


def render_json_line(registry: MetricsRegistry, names: Iterable[str]) -> str:
    """One-line JSON snapshot of selected histogram families (bench.py)."""
    return json.dumps(
        {n: registry.histogram_quantiles(n) for n in names}, sort_keys=True
    )
