"""Per-request resource attribution and the per-app cost ledger.

The fleet measures everything in aggregate (metrics, the device-efficiency
roofline, federation) but nothing says *who* consumed the device time, XLA
flops/bytes, or storage bytes — the prerequisite for multi-tenant quotas
(ROADMAP item 4) and the cost-performance framing applied per customer.
This module closes that gap in two layers:

- :class:`RequestCost` — a contextvar-scoped accumulator bound by the HTTP
  request handlers (the twin of ``obs.device.wave_timeline`` one level up):
  storage reads note bytes into it wherever they run on the request's own
  thread, and MicroBatcher waves hand their measured ``device_s`` +
  ``jit_cost_analysis`` flops/bytes back through per-item meta, prorated
  across wave members by batch share (:func:`prorated_from_meta`).
- :class:`CostLedger` — thread-safe time-windowed rollups keyed by
  ``(app, route, variant)``: device-seconds, flop-equivalents, HBM bytes,
  storage bytes, queue-seconds, cache hits/misses, shed counts.  Closed
  windows persist with the tmp+fsync+``os.replace`` discipline (the RES003
  idiom), so a SIGKILL loses at most the open window.  The ledger feeds
  ``/costs.json`` (obs/http.py), the router federation (fleet/federation),
  ``pio costs`` / ``pio top``, and the ``cost_burn`` / ``cost_skew`` alert
  rules (obs/alerts.py ``costs.*`` selectors).

Import-light by design (metrics + device only, neither touches jax at
module scope): the storage tier calls :func:`note_storage_read` on every
segment read without dragging an accelerator stack into the event server.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Mapping

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

log = logging.getLogger("predictionio_tpu.costs")

#: bump when the persisted ledger layout changes (loads refuse a mismatch
#: rather than guessing — same contract as the BENCH schema)
COST_SCHEMA_VERSION = 1

#: the numeric fields one cost row accumulates; RequestCost carries the
#: same names so billing a record into the ledger is one loop
COST_FIELDS: tuple[str, ...] = (
    "requests",
    "device_s",
    "flops",
    "hbm_bytes",
    "storage_bytes",
    "queue_s",
    "cache_hits",
    "cache_misses",
    "sheds",
)


class RequestCost:
    """One request's attributed resource record (contextvar-scoped)."""

    __slots__ = ("app", "route", "variant") + COST_FIELDS

    def __init__(
        self,
        app: str = "unknown",
        route: str = "",
        variant: str = "default",
    ):
        self.app = app
        self.route = route
        self.variant = variant
        for f in COST_FIELDS:
            setattr(self, f, 0.0)
        self.requests = 1.0

    def add(self, **fields: float) -> None:
        for name, amount in fields.items():
            if name not in COST_FIELDS:
                raise ValueError(f"unknown cost field {name!r}")
            setattr(self, name, getattr(self, name) + float(amount))

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "app": self.app,
            "route": self.route,
            "variant": self.variant,
        }
        for f in COST_FIELDS:
            d[f] = getattr(self, f)
        return d


_cost_var: contextvars.ContextVar[RequestCost | None] = (
    contextvars.ContextVar("pio_request_cost", default=None)
)


def current_cost() -> RequestCost | None:
    return _cost_var.get()


@contextlib.contextmanager
def request_cost(
    app: str,
    route: str,
    variant: str = "default",
    ledger: "CostLedger | None" = None,
) -> Iterator[RequestCost]:
    """Bind a fresh :class:`RequestCost` for the duration of one request;
    when ``ledger`` is given the record is billed on exit (accounting must
    never fail the request, so billing errors are logged, not raised)."""
    rec = RequestCost(app, route, variant)
    token = _cost_var.set(rec)
    try:
        yield rec
    finally:
        _cost_var.reset(token)
        if ledger is not None:
            try:
                ledger.bill(rec)
            except Exception:
                log.exception("cost billing failed (app=%s)", rec.app)


def note_storage_read(nbytes: float) -> None:
    """Bill ``nbytes`` of storage reads to whoever is asking: the bound
    request record when the read runs on a request thread, else the open
    wave timeline (MicroBatcher worker/finalizer — the wave total is
    prorated back to members through per-item meta).  No-op outside both
    scopes (training scans, tooling), and deliberately allocation-free:
    this sits on the per-row-group read path."""
    if nbytes <= 0:
        return
    rec = _cost_var.get()
    if rec is not None:
        rec.storage_bytes += nbytes
        return
    tl = device_obs.current_timeline()
    if tl is not None:
        tl.storage_bytes += nbytes


def prorated_from_meta(meta: Mapping[str, Any]) -> dict[str, float]:
    """A wave member's share of its wave's measured cost: the wave-level
    ``device_s`` / flops / bytes in per-item meta (microbatch._fill_meta)
    split evenly across the ``wave_size`` members that rode it.  Queue wait
    is per-item already and passes through unsplit."""
    n = max(int(meta.get("wave_size") or 1), 1)
    return {
        "device_s": float(meta.get("device_s") or 0.0) / n,
        "flops": float(meta.get("wave_flops") or 0.0) / n,
        "hbm_bytes": float(meta.get("wave_bytes") or 0.0) / n,
        "storage_bytes": float(meta.get("wave_storage_bytes") or 0.0) / n,
        "queue_s": float(meta.get("queue_wait_s") or 0.0),
        "cache_hits": float(meta.get("cache_hits") or 0.0) / n,
        "cache_misses": float(meta.get("cache_misses") or 0.0) / n,
    }


def budgets_from_env(
    env: Mapping[str, str] | None = None,
) -> tuple[dict[str, float], float | None]:
    """(per-app device-s/min budgets, default budget) from
    ``PIO_COST_BUDGETS`` (JSON object app -> budget) and
    ``PIO_COST_BUDGET_DEVICE_S_PER_MIN`` (fallback for any app).  A
    malformed budget map raises — silently dropping an operator's budget
    would fake an unlimited fleet."""
    e = env if env is not None else os.environ
    budgets: dict[str, float] = {}
    raw = e.get("PIO_COST_BUDGETS")
    if raw:
        plan = json.loads(raw)
        if not isinstance(plan, dict):
            raise ValueError("PIO_COST_BUDGETS must be a JSON object")
        budgets = {str(k): float(v) for k, v in plan.items()}
    default = None
    raw_default = e.get("PIO_COST_BUDGET_DEVICE_S_PER_MIN")
    if raw_default:
        default = float(raw_default)
    return budgets, default


class CostLedger:
    """Thread-safe windowed per-(app, route, variant) cost rollups.

    One open window accumulates live; on roll it closes into a bounded
    deque of historical windows and — when a ``path`` is configured — the
    closed set persists crash-safe (unique tmp + fsync + ``os.replace``),
    so a SIGKILL loses at most the open window.  Aggregate mirrors go to
    the metrics registry (``pio_cost_*_total{app,route,variant}``) so the
    conservation property is checkable: per-app attributed sums equal the
    registry counters exactly (both are fed by the same ``bill`` call
    under the same lock).
    """

    def __init__(
        self,
        window_s: float = 60.0,
        retention: int = 60,
        path: str | None = None,
        budgets: dict[str, float] | None = None,
        default_budget: float | None = None,
        registry: MetricsRegistry | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.window_s = float(window_s)
        self.retention = max(int(retention), 1)
        self.path = path
        if budgets is None and default_budget is None:
            budgets, default_budget = budgets_from_env()
        self.budgets = dict(budgets or {})
        self.default_budget = default_budget
        self._clock = clock
        # reentrant: _roll_locked re-acquires under billing/snapshot callers
        self._lock = threading.RLock()
        self._open: dict[tuple[str, str, str], dict[str, float]] = {}
        self._open_start = clock()
        self._closed: deque[dict[str, Any]] = deque(maxlen=self.retention)
        reg = registry or REGISTRY
        labels = ("app", "route", "variant")
        self._m = {
            "requests": reg.counter(
                "pio_cost_requests_total",
                "Requests billed to the cost ledger",
                labelnames=labels,
            ),
            "device_s": reg.counter(
                "pio_cost_device_seconds_total",
                "Attributed device-seconds by app/route/variant",
                labelnames=labels,
            ),
            "flops": reg.counter(
                "pio_cost_flops_total",
                "Attributed XLA cost-model flops by app/route/variant",
                labelnames=labels,
            ),
            "hbm_bytes": reg.counter(
                "pio_cost_hbm_bytes_total",
                "Attributed XLA cost-model bytes by app/route/variant",
                labelnames=labels,
            ),
            "storage_bytes": reg.counter(
                "pio_cost_storage_bytes_total",
                "Attributed event-store bytes read by app/route/variant",
                labelnames=labels,
            ),
            "queue_s": reg.counter(
                "pio_cost_queue_seconds_total",
                "Attributed micro-batch queue wait by app/route/variant",
                labelnames=labels,
            ),
            "sheds": reg.counter(
                "pio_cost_sheds_total",
                "Shed requests billed by app/route/variant",
                labelnames=labels,
            ),
        }
        if self.path:
            self._load()

    # -- billing -------------------------------------------------------------

    def bill(self, cost: RequestCost) -> None:
        self.bill_values(
            cost.app,
            cost.route,
            cost.variant,
            **{f: getattr(cost, f) for f in COST_FIELDS},
        )

    def bill_values(
        self, app: str, route: str, variant: str = "default", **fields: float
    ) -> None:
        """Accumulate one attribution into the open window (rolling it
        first if its end has passed) and mirror to the registry counters."""
        now = self._clock()
        key = (str(app), str(route), str(variant))
        with self._lock:
            self._roll_locked(now)
            row = self._open.get(key)
            if row is None:
                row = dict.fromkeys(COST_FIELDS, 0.0)
                self._open[key] = row
            for name, amount in fields.items():
                if name not in COST_FIELDS:
                    raise ValueError(f"unknown cost field {name!r}")
                row[name] += float(amount)
        for name, counter in self._m.items():
            amount = float(fields.get(name, 0.0))
            if amount > 0:
                counter.labels(*key).inc(amount)

    def bill_meta(
        self,
        app: str,
        route: str,
        variant: str,
        meta: Mapping[str, Any],
        queue_only: bool = False,
    ) -> None:
        """Bill one served request from its wave meta (the prorated share),
        or just its queue wait when the wave never computed for it."""
        shares = prorated_from_meta(meta)
        if queue_only:
            shares = {"queue_s": shares["queue_s"]}
        self.bill_values(app, route, variant, requests=1.0, **shares)

    def note_shed(
        self, app: str, route: str, variant: str = "default"
    ) -> None:
        self.bill_values(app, route, variant, sheds=1.0)

    # -- windowing -----------------------------------------------------------

    def _roll_locked(self, now: float) -> None:
        # the RLock makes the re-acquire free for callers already holding it
        with self._lock:
            rolled = False
            while now >= self._open_start + self.window_s:
                end = self._open_start + self.window_s
                if self._open:
                    self._closed.append(
                        {
                            "start": self._open_start,
                            "end": end,
                            "rows": [
                                {
                                    "app": k[0],
                                    "route": k[1],
                                    "variant": k[2],
                                    **row,
                                }
                                for k, row in sorted(self._open.items())
                            ],
                        }
                    )
                    self._open = {}
                    rolled = True
                self._open_start = end
                # a long-idle ledger fast-forwards: nothing accrued, so the
                # open window simply re-anchors at the current period
                if now - self._open_start > self.retention * self.window_s:
                    self._open_start = now
                    break
            if rolled and self.path:
                try:
                    self._persist_locked()
                except Exception:
                    log.exception("cost ledger persist failed (%s)", self.path)

    def roll(self, now: float | None = None) -> None:
        """Close any elapsed window (tests and the snapshot path drive
        this; billing rolls implicitly)."""
        with self._lock:
            self._roll_locked(self._clock() if now is None else now)

    # -- persistence (the RES003 tmp+fsync+replace idiom) --------------------

    def _persist_locked(self) -> None:
        final = self.path
        assert final is not None
        os.makedirs(os.path.dirname(final) or ".", exist_ok=True)
        doc = {
            "schema": COST_SCHEMA_VERSION,
            "window_s": self.window_s,
            "closed": list(self._closed),
        }
        data = json.dumps(doc, sort_keys=True)
        tmp = f"{final}.tmp-{os.getpid()}-{threading.get_ident()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.replace(tmp, final)

    def _load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except FileNotFoundError:
            return
        except Exception:
            log.exception("cost ledger load failed (%s); starting empty",
                          self.path)
            return
        if doc.get("schema") != COST_SCHEMA_VERSION:
            log.warning(
                "cost ledger %s has schema %s (want %s); starting empty",
                self.path, doc.get("schema"), COST_SCHEMA_VERSION,
            )
            return
        with self._lock:
            for w in doc.get("closed") or []:
                self._closed.append(w)

    # -- read side -----------------------------------------------------------

    def snapshot(self, windows: int | None = None) -> dict[str, Any]:
        """The ``/costs.json`` body: the open window, the last ``windows``
        closed windows (default all retained), and per-key totals across
        both — rows sorted by attributed device-seconds, heaviest first."""
        now = self._clock()
        with self._lock:
            self._roll_locked(now)
            open_rows = [
                {"app": k[0], "route": k[1], "variant": k[2], **row}
                for k, row in sorted(self._open.items())
            ]
            closed = list(self._closed)
            open_start = self._open_start
        if windows is not None:
            closed = closed[-max(int(windows), 0):]
        totals: dict[tuple[str, str, str], dict[str, float]] = {}
        for row in open_rows + [
            r for w in closed for r in w.get("rows", [])
        ]:
            key = (row["app"], row["route"], row["variant"])
            agg = totals.setdefault(key, dict.fromkeys(COST_FIELDS, 0.0))
            for f in COST_FIELDS:
                agg[f] += float(row.get(f, 0.0))
        total_rows = [
            {"app": k[0], "route": k[1], "variant": k[2], **agg}
            for k, agg in sorted(
                totals.items(),
                key=lambda kv: -kv[1]["device_s"],
            )
        ]
        return {
            "generated_at": now,
            "schema": COST_SCHEMA_VERSION,
            "window_s": self.window_s,
            "open": {"start": open_start, "rows": open_rows},
            "windows": closed,
            "totals": total_rows,
            "budgets": {
                "per_app": dict(self.budgets),
                "default_device_s_per_min": self.default_budget,
            },
        }

    # -- alert signals (obs/alerts.py ``costs.*`` selectors) -----------------

    def _per_app_device_s(self, now: float) -> tuple[dict[str, float], float]:
        """(per-app device-seconds over the current accounting window,
        seconds the window has covered).  Uses the open window; when it is
        empty (a roll just happened) the last closed window stands in, so
        a skew signal never flaps to silence at each window boundary."""
        with self._lock:
            self._roll_locked(now)
            if self._open:
                per_app: dict[str, float] = {}
                for (app, _r, _v), row in self._open.items():
                    per_app[app] = per_app.get(app, 0.0) + row["device_s"]
                return per_app, max(now - self._open_start, 1.0)
            if self._closed:
                last = self._closed[-1]
                per_app = {}
                for row in last.get("rows", []):
                    per_app[row["app"]] = (
                        per_app.get(row["app"], 0.0)
                        + float(row.get("device_s", 0.0))
                    )
                return per_app, self.window_s
        return {}, self.window_s

    def signal(self, name: str) -> dict[str, float]:
        """Per-app values for one ``costs.*`` alert selector.

        - ``burn_vs_budget``: (device-seconds/min) / budget, only for apps
          with a configured (or default) budget — 1.0 means burning the
          budget exactly;
        - ``device_share``: each app's fraction of total attributed device
          time; silent until at least two apps have device time, so a
          single-tenant deploy can't page itself for "consuming" 100 %.
        """
        now = self._clock()
        per_app, covered_s = self._per_app_device_s(now)
        if name == "burn_vs_budget":
            out: dict[str, float] = {}
            for app, dev_s in per_app.items():
                budget = self.budgets.get(app, self.default_budget)
                if budget is None or budget <= 0:
                    continue
                out[app] = (dev_s / covered_s * 60.0) / budget
            return out
        if name == "device_share":
            spenders = {a: v for a, v in per_app.items() if v > 0}
            total = sum(spenders.values())
            if len(spenders) < 2 or total <= 0:
                return {}
            return {a: v / total for a, v in spenders.items()}
        log.warning("cost ledger: unknown signal %s", name)
        return {}


# ---------------------------------------------------------------------------
# the process-default ledger (the default_quality idiom): single-VM deploys
# run the event server and prediction server in one process, and both must
# bill into the same rollup for /costs.json to answer "who costs what"

_default_lock = threading.Lock()
_DEFAULT: CostLedger | None = None


def default_ledger() -> CostLedger:
    global _DEFAULT
    if _DEFAULT is None:
        with _default_lock:
            if _DEFAULT is None:
                cost_dir = os.environ.get("PIO_COST_DIR")
                path = (
                    os.path.join(cost_dir, "costs.json") if cost_dir else None
                )
                try:
                    window_s = float(
                        os.environ.get("PIO_COST_WINDOW_S", "60")
                    )
                except ValueError:
                    window_s = 60.0
                _DEFAULT = CostLedger(window_s=window_s, path=path)
    return _DEFAULT


def reset_default_ledger() -> None:
    """Drop the process-default ledger (tests re-read the env)."""
    global _DEFAULT
    with _default_lock:
        _DEFAULT = None


# ---------------------------------------------------------------------------
# text rendering (pio costs)


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def render_costs_text(doc: Mapping[str, Any]) -> str:
    """Human table over a /costs.json body — local or federated (the
    federated shape carries ``replicas`` and replica-tagged rows)."""
    lines: list[str] = []
    replicas = doc.get("replicas")
    if replicas:
        lines.append(
            f"fleet costs across {len(replicas)} replica(s): "
            + ", ".join(replicas)
        )
        errors = doc.get("source_errors") or {}
        for name, err in sorted(errors.items()):
            lines.append(f"  ! {name}: {err}")
    header = (
        f"{'APP':<16} {'ROUTE':<18} {'VARIANT':<10} {'REQS':>8} "
        f"{'DEVICE_S':>10} {'FLOPS':>12} {'STORAGE':>10} {'QUEUE_S':>8} "
        f"{'SHEDS':>6}"
    )
    lines.append(header)
    rows = doc.get("totals") or []
    if not rows:
        lines.append("(no attributed cost yet)")
    for row in rows:
        app = str(row.get("app", "?"))
        if row.get("replica"):
            app = f"{app}@{row['replica']}"
        lines.append(
            f"{app:<16.16} {str(row.get('route', '')):<18.18} "
            f"{str(row.get('variant', '')):<10.10} "
            f"{int(row.get('requests', 0)):>8} "
            f"{float(row.get('device_s', 0.0)):>10.4f} "
            f"{float(row.get('flops', 0.0)):>12.3e} "
            f"{_fmt_bytes(float(row.get('storage_bytes', 0.0))):>10} "
            f"{float(row.get('queue_s', 0.0)):>8.3f} "
            f"{int(row.get('sheds', 0)):>6}"
        )
    budgets = doc.get("budgets") or {}
    per_app = budgets.get("per_app") or {}
    if per_app or budgets.get("default_device_s_per_min"):
        lines.append("")
        lines.append(
            "budgets (device-s/min): "
            + ", ".join(f"{a}={b}" for a, b in sorted(per_app.items()))
            + (
                f" default={budgets['default_device_s_per_min']}"
                if budgets.get("default_device_s_per_min")
                else ""
            )
        )
    return "\n".join(lines) + "\n"
