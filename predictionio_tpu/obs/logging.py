"""Structured (JSON-lines) logging with request-id correlation.

The request-lifecycle half of the observability story: every log record can
carry a ``request_id``/``trace_id`` pair propagated through
:mod:`contextvars`, so one slow query is greppable across the aio front end,
the route handler, and the MicroBatcher wave that served it — the
correlation practice large-scale serving systems treat as table stakes
(SURVEY.md §5.8).

Three pieces:

- contextvar helpers (:func:`set_request_context` / :func:`get_request_id`)
  that the HTTP front ends set per request and everything else reads;
- :class:`JsonLineFormatter`, a collector-parseable one-JSON-object-per-line
  formatter that folds in the context ids and any ``extra=`` fields;
- :class:`LogRing`, a bounded in-process ring of recent records served at
  ``GET /logs.json`` so "what did the server just log" is answerable without
  shipping logs anywhere.

:func:`configure_logging` is the single entry point the ``pio`` CLI and the
standalone servers adopt (replacing ad-hoc ``logging.basicConfig`` calls):
JSON lines to stderr by default (``PIO_LOG_FORMAT=text`` for humans), ring
always attached.  Everything is stdlib-only.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import secrets
import sys
import threading
import time
from collections import deque
from typing import Any, TextIO

#: per-request correlation ids; set by the HTTP front ends, read everywhere
_request_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_request_id", default=None
)
_trace_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "pio_trace_id", default=None
)

#: header under which request ids travel (request and response)
REQUEST_ID_HEADER = "X-Pio-Request-Id"


def new_request_id() -> str:
    """Mint a 16-hex-char request id (collision-safe at fleet scale)."""
    return secrets.token_hex(8)


def set_request_context(
    request_id: str | None, trace_id: str | None = None
) -> tuple[contextvars.Token, contextvars.Token]:
    """Bind correlation ids to the current context; returns reset tokens."""
    return (
        _request_id_var.set(request_id),
        _trace_id_var.set(trace_id or request_id),
    )


def reset_request_context(
    tokens: tuple[contextvars.Token, contextvars.Token]
) -> None:
    _request_id_var.reset(tokens[0])
    _trace_id_var.reset(tokens[1])


def get_request_id() -> str | None:
    return _request_id_var.get()


def get_trace_id() -> str | None:
    return _trace_id_var.get()


#: LogRecord attributes that are plumbing, not user-supplied extras
_RESERVED = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


def record_fields(record: logging.LogRecord) -> dict[str, Any]:
    """A log record as a flat JSON-safe dict: timestamp, level, logger,
    message, the contextvar correlation ids, and any ``extra=`` fields."""
    fields: dict[str, Any] = {
        "ts": round(record.created, 6),
        "level": record.levelname,
        "logger": record.name,
        "message": record.getMessage(),
    }
    rid = _request_id_var.get()
    if rid:
        fields["request_id"] = rid
    tid = _trace_id_var.get()
    if tid and tid != rid:
        fields["trace_id"] = tid
    for k, v in record.__dict__.items():
        if k not in _RESERVED and not k.startswith("_"):
            fields[k] = v
    if record.exc_info and record.exc_info[0] is not None:
        fields["exc"] = logging.Formatter().formatException(record.exc_info)
    return fields


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line — what a log collector actually wants."""

    def format(self, record: logging.LogRecord) -> str:
        return json.dumps(record_fields(record), default=str, sort_keys=True)


class LogRing(logging.Handler):
    """Bounded ring of recent structured records, served at /logs.json.

    ``emit`` stores the flat field dict (not the formatted string) so the
    HTTP route can filter by ``request_id``/``level`` without re-parsing.
    Uses the Handler's own lock for the deque so readers never race emit.
    """

    def __init__(self, maxlen: int = 1024, level: int = logging.DEBUG):
        super().__init__(level=level)
        self._ring: deque[dict[str, Any]] = deque(maxlen=maxlen)

    def emit(self, record: logging.LogRecord) -> None:
        if getattr(record, "_pio_ring_skip", False):
            return  # already ring_append()ed directly — no duplicate
        try:
            fields = record_fields(record)
        except Exception:  # telemetry must never break the caller
            return
        with self.lock:
            self._ring.append(fields)

    def append_fields(self, fields: dict[str, Any]) -> None:
        """Direct append, bypassing the logging pipeline (see ring_debug)."""
        with self.lock:
            self._ring.append(fields)

    def records(
        self,
        limit: int = 100,
        request_id: str | None = None,
        min_level: str | None = None,
    ) -> list[dict[str, Any]]:
        """Most recent matching records, newest first."""
        with self.lock:
            items = list(self._ring)
        if request_id is not None:
            items = [
                f
                for f in items
                if f.get("request_id") == request_id
                or request_id in (f.get("request_ids") or ())
            ]
        if min_level is not None:
            threshold = logging.getLevelName(min_level.upper())
            if isinstance(threshold, int):
                items = [
                    f
                    for f in items
                    if logging.getLevelName(f.get("level", "NOTSET"))
                    >= threshold
                ]
        return items[::-1][: max(limit, 0)]

    def clear(self) -> None:
        with self.lock:
            self._ring.clear()


_state_lock = threading.Lock()
_ring: LogRing | None = None


def ensure_ring(maxlen: int = 1024) -> LogRing:
    """Attach the process log ring to the package logger (idempotent).

    Deliberately does NOT touch logger levels: forcing the package logger
    to DEBUG would leak debug records through any embedding application's
    level-less root handlers (``logging.basicConfig`` users).  The ring
    sees whatever the host's logging config lets through; correlation-
    critical lines use :func:`ring_debug`, which reaches the ring
    unconditionally.  :func:`configure_logging` (the CLI / standalone-
    server path, where we own the handlers) opens the package logger to
    DEBUG so the ring captures everything.
    """
    global _ring
    with _state_lock:
        if _ring is None:
            _ring = LogRing(maxlen=maxlen)
            logging.getLogger("predictionio_tpu").addHandler(_ring)
        return _ring


def get_log_ring() -> LogRing:
    return ensure_ring()


def ring_debug(logger: logging.Logger, message: str, **fields: Any) -> None:
    """Emit a correlation record that ALWAYS reaches the /logs.json ring,
    regardless of the host's logging configuration, and flows through
    normal logging at DEBUG only when the logger is enabled for it (flagged
    so the ring handler doesn't record it twice).  Used for the
    request-correlation lines — e.g. the MicroBatcher's per-wave
    request_ids — whose whole purpose is being findable later."""
    entry: dict[str, Any] = {
        "ts": round(time.time(), 6),
        "level": "DEBUG",
        "logger": logger.name,
        "message": message,
    }
    rid = _request_id_var.get()
    if rid:
        entry["request_id"] = rid
    entry.update(fields)
    ensure_ring().append_fields(entry)
    if logger.isEnabledFor(logging.DEBUG):
        logger.debug(message, extra={**fields, "_pio_ring_skip": True})


def configure_logging(
    level: str | int | None = None,
    stream: TextIO | None = None,
    fmt: str | None = None,
    ring_size: int = 1024,
) -> LogRing:
    """Process-wide logging setup for the CLI and standalone servers.

    JSON lines (default) or classic text (``fmt="text"`` /
    ``PIO_LOG_FORMAT=text``) to ``stream`` (default stderr) at ``level``
    (default ``PIO_LOG_LEVEL`` or INFO; a typo'd env var must not crash
    every verb), plus the bounded ring at DEBUG.  Idempotent: calling again
    replaces the handler this function installed, never third-party ones.
    """
    if level is None:
        level = os.environ.get("PIO_LOG_LEVEL", "INFO").upper()
    if isinstance(level, str):
        resolved = getattr(logging, level.upper(), None)
        level = resolved if isinstance(resolved, int) else logging.INFO
    fmt = (fmt or os.environ.get("PIO_LOG_FORMAT", "json")).lower()
    ring = ensure_ring(ring_size)
    # we own the handler levels from here on, so opening the package logger
    # to DEBUG feeds the ring everything without spamming the console
    logging.getLogger("predictionio_tpu").setLevel(logging.DEBUG)
    root = logging.getLogger()
    for h in list(root.handlers):
        if getattr(h, "_pio_structured", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setLevel(level)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        if fmt == "text"
        else JsonLineFormatter()
    )
    handler._pio_structured = True  # type: ignore[attr-defined]
    root.addHandler(handler)
    root.setLevel(level)
    return ring
