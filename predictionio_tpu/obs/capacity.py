"""Capacity / headroom model: observed load → "how much more can this
replica take, and how many replicas should exist".

ROADMAP item 4 names SLO burn rates as the autoscaling signal; this module
is the join that turns the raw observability the earlier PRs built into
that signal.  Inputs, all already metered:

- **device throughput** — ``pio_microbatch_batch_size`` ÷
  ``pio_microbatch_device_seconds`` (histogram sums): queries the device
  path completes per busy second.  The MicroBatcher serializes waves on one
  worker, so this is the per-replica device ceiling.
- **admission ceiling** — Little's law over the in-flight cap:
  ``max_inflight / mean request latency`` is the arrival rate past which
  admission control starts shedding.
- **queue occupancy** — ``pio_microbatch_queue_depth`` against the queue
  bound: standing backlog means the ceiling is already being paid in
  latency.
- **observed load + SLO burn** — the rolling SLO window's request rate and
  burn rates (obs/slo.py).

Outputs: ``max_sustainable_qps`` (the binding ceiling and which input
binds), ``headroom_frac`` (1 − load/ceiling, clamped to [-1, 1]), and a
``recommended_replicas`` integer sized so the fleet would run at
:data:`TARGET_UTILIZATION` of its ceiling — the input a horizontal
autoscaler (or an operator reading the dashboard Capacity panel) acts on.

Estimates are cheap arithmetic over already-collected counters — a scrape,
not a load test — and honest about their blind spots: with no device
traffic yet there is no device ceiling, and the snapshot says so in
``caveats`` instead of inventing one.
"""

from __future__ import annotations

import math
from typing import Any, Mapping

from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

#: fleet sizing targets this utilization of the binding ceiling — the
#: standard "scale before the knee" margin
TARGET_UTILIZATION = 0.7

#: burn rate past which the model stops trusting its own headroom math and
#: recommends scaling regardless (the SLO is ALREADY burning)
BURN_LIMIT = 1.0


def _family_totals(
    registry: MetricsRegistry, name: str
) -> tuple[float, float]:
    """(sum, count) across every series of one histogram family."""
    fam = registry.get(name)
    if fam is None or fam.kind != "histogram":
        return 0.0, 0.0
    total_sum = 0.0
    total_count = 0.0
    for _, child in fam.series():
        _, s, c = child.snapshot()
        total_sum += s
        total_count += c
    return total_sum, total_count


def _gauge_value(registry: MetricsRegistry, name: str) -> float | None:
    fam = registry.get(name)
    if fam is None or fam.kind == "histogram":
        return None
    series = fam.series()
    if not series:
        return None
    return float(sum(child.value for _, child in series))


def capacity_snapshot(app: Any, registry: MetricsRegistry | None = None) -> dict:
    """The ``/capacity.json`` body for one serving app (``app`` may be None
    for a process-local `pio capacity` dump — admission/SLO inputs are then
    simply absent)."""
    reg = registry or REGISTRY
    caveats: list[str] = []

    # -- device ceiling: queries per device-busy second ----------------------
    size_sum, _ = _family_totals(reg, "pio_microbatch_batch_size")
    dev_sum, dev_waves = _family_totals(reg, "pio_microbatch_device_seconds")
    device_qps = size_sum / dev_sum if dev_sum > 0 else None
    if device_qps is None:
        caveats.append("no micro-batched waves observed yet: no device ceiling")

    # -- observed load + latency --------------------------------------------
    lat_sum, lat_count = _family_totals(reg, "pio_request_latency_seconds")
    mean_latency_s = lat_sum / lat_count if lat_count > 0 else None
    slo = getattr(app, "slo", None) if app is not None else None
    observed_qps = None
    burn = {}
    if slo is not None:
        snap = slo.snapshot()
        window = min(snap["window_s"], max(snap["uptime_s"], 1e-9))
        observed_qps = snap["requests"] / window if window > 0 else None
        burn = {
            "error_burn_rate": snap["error_burn_rate"],
            "latency_burn_rate": snap["latency_burn_rate"],
            "slo_status": snap["status"],
        }
    else:
        caveats.append("no SLO tracker: observed load unknown")

    # -- admission ceiling (Little's law over the in-flight cap) -------------
    admission = getattr(app, "admission", None) if app is not None else None
    admission_qps = None
    inflight = None
    max_inflight = None
    if admission is not None:
        max_inflight = admission.max_inflight
        inflight = admission.inflight
        if mean_latency_s and mean_latency_s > 0:
            admission_qps = max_inflight / mean_latency_s
        else:
            caveats.append(
                "no request latency observed yet: admission ceiling unknown"
            )
    else:
        caveats.append("no admission cap configured: admission ceiling unbounded")

    # -- queue occupancy -----------------------------------------------------
    queue_depth = _gauge_value(reg, "pio_microbatch_queue_depth") or 0.0
    batcher = getattr(app, "microbatcher", None) if app is not None else None
    max_queue = getattr(batcher, "max_queue", None)
    # with no bound, occupancy is unknowable — a transient depth of 1
    # between submit and dispatch must NOT read as a full queue
    queue_frac = queue_depth / max_queue if max_queue else None
    if max_queue is None and queue_depth:
        caveats.append("queue unbounded: occupancy fraction not computable")

    # -- the join ------------------------------------------------------------
    ceilings: dict[str, float] = {}
    if device_qps is not None:
        ceilings["device"] = round(device_qps, 3)
    if admission_qps is not None:
        ceilings["admission"] = round(admission_qps, 3)
    binding = min(ceilings, key=ceilings.get) if ceilings else None
    max_qps = ceilings[binding] if binding else None

    headroom = None
    if max_qps is not None and observed_qps is not None and max_qps > 0:
        headroom = max(min(1.0 - observed_qps / max_qps, 1.0), -1.0)
    burning = max(
        burn.get("error_burn_rate", 0.0), burn.get("latency_burn_rate", 0.0)
    ) > BURN_LIMIT
    if burning and headroom is not None:
        # the SLO is already missing: whatever the arithmetic says, this
        # replica has no spendable headroom
        headroom = min(headroom, 0.0)

    recommended = None
    if max_qps is not None and observed_qps is not None and max_qps > 0:
        recommended = max(
            1, math.ceil(observed_qps / (TARGET_UTILIZATION * max_qps))
        )
        if burning:
            recommended += 1

    scale_hint = "unknown"
    if burning:
        # the SLO is ALREADY burning: even with no computable ceiling the
        # signal must not go dark at the exact moment it matters most
        scale_hint = "up"
        if headroom is None:
            caveats.append(
                "SLO burning with no computable ceiling: scale up on burn "
                "rate alone"
            )
    elif headroom is not None:
        if headroom <= 0.0 or (queue_frac is not None and queue_frac > 0.5):
            scale_hint = "up"
        elif headroom > 1.0 - TARGET_UTILIZATION:
            scale_hint = "hold_or_down"
        else:
            scale_hint = "hold"

    return {
        "inputs": {
            "device_items_per_busy_second": (
                round(device_qps, 3) if device_qps is not None else None
            ),
            "device_busy_seconds": round(dev_sum, 6),
            "waves": int(dev_waves),
            "mean_request_latency_s": (
                round(mean_latency_s, 6) if mean_latency_s is not None else None
            ),
            "observed_qps": (
                round(observed_qps, 3) if observed_qps is not None else None
            ),
            "inflight": inflight,
            "max_inflight": max_inflight,
            "queue_depth": queue_depth,
            "max_queue": max_queue,
            "queue_occupancy_frac": (
                round(queue_frac, 4) if queue_frac is not None else None
            ),
            **burn,
        },
        "ceilings_qps": ceilings,
        "binding_ceiling": binding,
        "max_sustainable_qps": max_qps,
        "headroom_frac": round(headroom, 4) if headroom is not None else None,
        "recommended_replicas": recommended,
        "scale_hint": scale_hint,
        "target_utilization": TARGET_UTILIZATION,
        "caveats": caveats,
    }


def render_capacity_text(snap: Mapping[str, Any]) -> str:
    """Human one-screen rendering of a /capacity.json body — including the
    fleet-aggregated shape a router serves (a ``fleet`` block with
    per-replica rows rides on top of the shared summary keys)."""
    inputs = snap.get("inputs", {})
    fleet = snap.get("fleet")
    if fleet:
        lines = [
            f"fleet:             {fleet.get('replicas', 0)} replicas "
            f"({fleet.get('routable', 0)} routable, "
            f"{fleet.get('active', 0)} active)",
        ]
        for rid, cap in sorted((fleet.get("per_replica") or {}).items()):
            if cap is None:
                lines.append(f"  {rid:<22} (no capacity scrape yet)")
                continue
            lines.append(
                f"  {rid:<22} max {_fmt(cap.get('max_sustainable_qps'))} qps, "
                f"observed {_fmt(cap.get('observed_qps'))} qps, headroom "
                + (
                    f"{cap['headroom_frac']:.1%}"
                    if isinstance(cap.get("headroom_frac"), (int, float))
                    else "n/a"
                )
            )
        lines += [
            "",
            f"max sustainable:   {_fmt(snap.get('max_sustainable_qps'))} qps "
            "(sum of replica ceilings)",
            f"headroom:          "
            + (
                f"{snap['headroom_frac']:.1%} (worst replica)"
                if snap.get("headroom_frac") is not None
                else "n/a"
            ),
            f"recommended replicas: {snap.get('recommended_replicas') or 'n/a'} "
            f"(fleet-wide, sized for "
            f"{snap.get('target_utilization', TARGET_UTILIZATION):.0%} "
            f"utilization)   scale hint: {snap.get('scale_hint')}",
        ]
        for c in snap.get("caveats", []):
            lines.append(f"caveat: {c}")
        return "\n".join(lines)
    lines = [
        f"observed load:     {_fmt(inputs.get('observed_qps'))} qps "
        f"(mean latency {_fmt_ms(inputs.get('mean_request_latency_s'))})",
        f"device ceiling:    {_fmt(snap.get('ceilings_qps', {}).get('device'))} qps "
        f"({inputs.get('waves', 0)} waves, "
        f"{inputs.get('device_busy_seconds', 0.0):.3f}s busy)",
        f"admission ceiling: {_fmt(snap.get('ceilings_qps', {}).get('admission'))} qps "
        f"(in-flight {inputs.get('inflight')}/{inputs.get('max_inflight')})",
        f"queue:             {inputs.get('queue_depth', 0):g} queued "
        + (
            f"({inputs['queue_occupancy_frac']:.1%} of bound)"
            if inputs.get("queue_occupancy_frac") is not None
            else "(no bound)"
        ),
        f"slo:               {inputs.get('slo_status', 'n/a')} "
        f"(error burn {inputs.get('error_burn_rate', 0.0)}, "
        f"latency burn {inputs.get('latency_burn_rate', 0.0)})",
        "",
        f"max sustainable:   {_fmt(snap.get('max_sustainable_qps'))} qps "
        f"(binding: {snap.get('binding_ceiling') or 'n/a'})",
        f"headroom:          "
        + (
            f"{snap['headroom_frac']:.1%}"
            if snap.get("headroom_frac") is not None
            else "n/a"
        ),
        f"recommended replicas: {snap.get('recommended_replicas') or 'n/a'} "
        f"(sized for {snap.get('target_utilization', TARGET_UTILIZATION):.0%} "
        f"utilization)   scale hint: {snap.get('scale_hint')}",
    ]
    for c in snap.get("caveats", []):
        lines.append(f"caveat: {c}")
    return "\n".join(lines)


def _fmt(v: Any) -> str:
    return f"{v:g}" if isinstance(v, (int, float)) else "n/a"


def _fmt_ms(v: Any) -> str:
    return f"{v * 1e3:.3f} ms" if isinstance(v, (int, float)) else "n/a"
