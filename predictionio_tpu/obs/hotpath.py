"""Solo-path host-stage attribution: where a single request's time goes.

BENCH_r05 measured the solo serving path paying ~100 ms of host-side
overhead around a 1.4 ms device cost (ROADMAP item 3d) — but the request
latency histogram is one opaque number, so "optimize the solo path" had no
starting breakdown.  This module decomposes every non-batched request into
named HOST stages, measured contiguously so they account for (almost) all
of the request's wall time:

========================  ==================================================
stage                     meaning
========================  ==================================================
``parse``                 body JSON decode + query-class extraction
``route``                 binding selection / canary split / handler prep
``queue_wait``            submit-to-dispatch wait behind the in-flight wave
                          (micro-batched front end only)
``entity_gather``         host-side feature/factor gather (``supplement``
                          and any engine ``host_gather`` marks)
``h2d``                   host→device transfer the engine marked
``compute``               device compute the engine marked
``d2h``                   device→host readback the engine marked
``dispatch``              the unattributed interior of the predict window:
                          kernel-launch / dev-tunnel overhead on device
                          engines, host scoring on host-replica engines
``block_until_ready``     event-loop wakeup + future resolution after the
                          wave finished (micro-batched front end only)
``serialize``             render, plugins/feedback, response build + encode
========================  ==================================================

Each stage lands in ``pio_hotpath_stage_seconds{stage}`` and in a
per-tracker mean table; ``GET /hotpath.json`` serves p50/p99-per-stage with
a ``coverage_frac`` — the fraction of solo wall time the named stages
explain, which the tests hold at ≥95 %.  The stages are measured with one
:class:`StageClock` per request: consecutive ``lap()`` marks, so the only
unattributed time is the slivers between marks.

**Overlap semantics (pipelined dispatch, PR 12):** once waves pipeline,
stage durations stop summing naively — a request's ``queue_wait`` can
overlap the previous wave's ``compute``, and the wave's device stages are
measured on the worker/finalizer clocks while the request's wall runs on
its own.  Coverage stays honest by construction: per request the
attributed total is clamped to the wall (``min(attributed, total)``), so
``coverage_frac`` can never exceed 1.0, and the clamped excess is
surfaced as ``overlap_frac`` — the fraction of attributed stage time that
ran CONCURRENTLY with other stages.  A rising ``overlap_frac`` with a
falling total p50 is the pipeline working; the ≥95 % coverage assertion
holds under overlap because clamping only ever discards double-counted
time, never real wall time.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Mapping

from predictionio_tpu.obs.metrics import (
    REGISTRY,
    MetricsRegistry,
    quantile_from_buckets,
)

#: canonical stage order for rendering (unknown stages append after)
STAGE_ORDER: tuple[str, ...] = (
    "parse",
    "route",
    "queue_wait",
    "entity_gather",
    "h2d",
    "compute",
    "d2h",
    "dispatch",
    "block_until_ready",
    "serialize",
)

#: map the wave timeline's device-breakdown keys onto hotpath stage names
WAVE_STAGE_MAP: Mapping[str, str] = {
    "host_gather": "entity_gather",
    "h2d": "h2d",
    "compute": "compute",
    "d2h": "d2h",
    "other": "dispatch",
}


class StageClock:
    """Consecutive stage marks for one request.

    ``lap(name)`` attributes everything since the previous mark to
    ``name``; ``add(name, seconds)`` folds in a single externally-measured
    duration while advancing the mark by the same amount, so
    externally-attributed time is never double counted by the next
    ``lap``; ``split(parts, remainder)`` does the same for a whole window
    of external measurements at once (how the serving front ends fold in
    the MicroBatcher's ``queue_wait_s``/device-breakdown meta).
    """

    __slots__ = ("t0", "_mark", "stages")

    def __init__(self):
        self.t0 = self._mark = time.perf_counter()
        self.stages: dict[str, float] = {}

    def lap(self, stage: str) -> float:
        now = time.perf_counter()
        dt = now - self._mark
        self._mark = now
        if dt > 0:
            self.stages[stage] = self.stages.get(stage, 0.0) + dt
        return dt

    def add(self, stage: str, seconds: float) -> None:
        if seconds and seconds > 0:
            self.stages[stage] = self.stages.get(stage, 0.0) + seconds
            self._mark += seconds

    def split(self, parts: Mapping[str, float], remainder: str) -> None:
        """Attribute the time since the previous mark: the named ``parts``
        first, whatever is left to ``remainder`` (clamped at zero — parts
        measured on another clock can slightly exceed the window)."""
        now = time.perf_counter()
        window = now - self._mark
        self._mark = now
        attributed = 0.0
        for name, seconds in parts.items():
            if seconds and seconds > 0:
                self.stages[name] = self.stages.get(name, 0.0) + seconds
                attributed += seconds
        left = window - attributed
        if left > 0:
            self.stages[remainder] = self.stages.get(remainder, 0.0) + left

    def total(self) -> float:
        return time.perf_counter() - self.t0


class HotPathTracker:
    """Aggregate per-stage durations + coverage for one serving app.

    ``observe`` is the per-request write (a handful of histogram
    observations plus two float adds under one lock); ``snapshot`` is the
    ``/hotpath.json`` body.
    """

    def __init__(self, registry: MetricsRegistry | None = None):
        reg = registry or REGISTRY
        self._fam = reg.histogram(
            "pio_hotpath_stage_seconds",
            "Solo-request host time by named hot-path stage",
            labelnames=("stage",),
        )
        self._total_hist = reg.histogram(
            "pio_hotpath_total_seconds",
            "Solo-request wall time covered by hot-path attribution",
        )
        self._lock = threading.Lock()
        self._n = 0
        self._total_sum = 0.0
        self._attributed_sum = 0.0
        self._overlap_sum = 0.0
        self._stage_sums: dict[str, float] = {}

    def observe(self, total_s: float, stages: Mapping[str, float]) -> None:
        if total_s <= 0:
            return
        attributed = 0.0
        for name, seconds in stages.items():
            if seconds and seconds > 0:
                self._fam.labels(name).observe(seconds)
                attributed += seconds
        self._total_hist.observe(total_s)
        with self._lock:
            self._n += 1
            self._total_sum += total_s
            # clamp: pipelined stages measured on other clocks can overlap
            # the request's own wall — coverage must never read >100 %
            self._attributed_sum += min(attributed, total_s)
            self._overlap_sum += max(attributed - total_s, 0.0)
            for name, seconds in stages.items():
                if seconds and seconds > 0:
                    self._stage_sums[name] = (
                        self._stage_sums.get(name, 0.0) + seconds
                    )

    def observe_clock(self, clock: StageClock) -> None:
        self.observe(clock.total(), clock.stages)

    def snapshot(self) -> dict[str, Any]:
        """Per-stage p50/p99/mean/share table + the coverage fraction the
        acceptance gate holds at ≥0.95."""
        with self._lock:
            n = self._n
            total_sum = self._total_sum
            attributed_sum = self._attributed_sum
            overlap_sum = self._overlap_sum
            stage_sums = dict(self._stage_sums)
        fam = self._fam
        order = {s: i for i, s in enumerate(STAGE_ORDER)}
        stages: dict[str, Any] = {}
        for name in sorted(
            stage_sums, key=lambda s: (order.get(s, len(order)), s)
        ):
            child = fam.labels(name)
            counts, _, count = child.snapshot()
            stages[name] = {
                "count": count,
                "seconds_total": round(stage_sums[name], 6),
                "share_frac": round(
                    stage_sums[name] / total_sum if total_sum else 0.0, 4
                ),
                "p50_s": round(
                    quantile_from_buckets(child.bounds, counts, count, 0.50), 9
                ),
                "p99_s": round(
                    quantile_from_buckets(child.bounds, counts, count, 0.99), 9
                ),
                "mean_s": round(
                    stage_sums[name] / count if count else 0.0, 9
                ),
            }
        tcounts, _, tcount = self._total_hist.snapshot()
        return {
            "requests": n,
            "coverage_frac": round(
                attributed_sum / total_sum if total_sum else 0.0, 4
            ),
            # stage time that ran concurrently with other stages (pipelined
            # dispatch): attributed-beyond-wall, as a fraction of wall
            "overlap_frac": round(
                overlap_sum / total_sum if total_sum else 0.0, 4
            ),
            "total": {
                "sum_s": round(total_sum, 6),
                "p50_s": round(
                    quantile_from_buckets(
                        self._total_hist.bounds, tcounts, tcount, 0.50
                    ),
                    9,
                ),
                "p99_s": round(
                    quantile_from_buckets(
                        self._total_hist.bounds, tcounts, tcount, 0.99
                    ),
                    9,
                ),
            },
            "stages": stages,
        }


def render_hotpath_text(snap: Mapping[str, Any]) -> str:
    """One-screen stage table over a ``/hotpath.json`` body — the
    ``# serving_hotpath`` lines in bench logs."""
    lines = [
        f"requests: {snap.get('requests', 0)}   "
        f"coverage: {snap.get('coverage_frac', 0.0):.1%}   "
        f"total p50 {snap.get('total', {}).get('p50_s', 0.0) * 1e3:.3f} ms / "
        f"p99 {snap.get('total', {}).get('p99_s', 0.0) * 1e3:.3f} ms",
        f"{'stage':<18} {'share':>7} {'p50 ms':>10} {'p99 ms':>10}",
    ]
    for name, row in snap.get("stages", {}).items():
        lines.append(
            f"{name:<18} {row['share_frac']:>6.1%} "
            f"{row['p50_s'] * 1e3:>10.3f} {row['p99_s'] * 1e3:>10.3f}"
        )
    return "\n".join(lines)
