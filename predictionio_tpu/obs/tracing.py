"""Lightweight per-stage spans feeding the metrics registry.

``trace("stage")`` is a context manager that times its block, records the
duration into the ``pio_span_seconds{span="stage"}`` histogram, and builds a
parent/child tree through a context-local span stack — nested ``trace``
blocks become children of the enclosing one.  Finished ROOT spans
additionally land in a bounded ring buffer (:func:`recent_traces`) so "what
did the last train run spend its time on" is answerable without a metrics
backend.

This is deliberately not OpenTelemetry: no export, no sampling — a span is a
(name, duration, children) record and one histogram observation.  Spans DO
carry the contextvar ``request_id`` (obs/logging.py) when one is bound, so a
``/traces.json`` entry correlates with the ``X-Pio-Request-Id`` response
header and the matching ``/logs.json`` lines.  The HTTP front ends open one
cheap unrecorded root span per request (``record=False``: ring only, no
histogram); the second-scale stages — DASE train stages, JAX compiles, batch
predict, eval folds — use recorded spans.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Any

from predictionio_tpu.obs.disttrace import (
    collect as _collect_fragments,
    get_parent_span,
    new_span_id,
)
from predictionio_tpu.obs.logging import get_request_id, get_trace_id
from predictionio_tpu.obs.metrics import (
    REGISTRY,
    STAGE_BUCKETS,
    TRAIN_BUCKETS,
    MetricsRegistry,
)

#: the span stack is a ContextVar (not a threading.local) so nesting is
#: correct both across threads AND across interleaved asyncio tasks — two
#: concurrent requests on one event loop must not adopt each other's spans
_stack_var: contextvars.ContextVar[list["Span"] | None] = (
    contextvars.ContextVar("pio_span_stack", default=None)
)

#: ring of the most recent finished root spans (as dicts), newest last
_ring: deque[dict[str, Any]] = deque(maxlen=256)
_ring_lock = threading.Lock()


class Span:
    """One timed block.  ``duration_s`` is valid after the block exits."""

    __slots__ = (
        "name", "start_s", "duration_s", "children", "error",
        "request_id", "tags", "span_id", "parent_id", "trace_id",
        "start_ts",
    )

    def __init__(self, name: str):
        self.name = name
        self.start_s = 0.0
        self.duration_s = 0.0
        self.children: list[Span] = []
        self.error: str | None = None
        #: correlation id captured from the request context at entry
        self.request_id: str | None = None
        #: small free-form annotations (route, status, ...) — keep it small;
        #: every root span's dict lands in the trace ring
        self.tags: dict[str, Any] | None = None
        #: distributed-tracing identity (obs/disttrace.py): a per-span id,
        #: the cross-process parent (root spans adopt X-Pio-Parent-Span),
        #: the trace this span belongs to, and a wall-clock start so
        #: fragments from different processes align on one timeline
        self.span_id: str = ""
        self.parent_id: str | None = None
        self.trace_id: str | None = None
        self.start_ts: float = 0.0

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "duration_s": round(self.duration_s, 9),
        }
        if self.request_id:
            d["request_id"] = self.request_id
        if self.trace_id:
            d["trace_id"] = self.trace_id
        if self.tags:
            d.update(self.tags)
        if self.error:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def breakdown(self) -> dict[str, float]:
        """Flat child-name → seconds map (duplicate names accumulate)."""
        out: dict[str, float] = {}
        for c in self.children:
            out[c.name] = out.get(c.name, 0.0) + c.duration_s
        return out


class trace:
    """Context manager: ``with trace("train.prepare") as span: ...``

    ``record=False`` skips the span-duration histogram; ``ring=False``
    keeps a ROOT span out of the recent-traces ring (for high-volume
    infrastructure spans like storage round trips that would otherwise
    evict real request traces from ``/traces.json``) — cross-process
    fragment collection is unaffected by either."""

    __slots__ = ("span", "_registry", "_record", "_ring")

    def __init__(
        self,
        name: str,
        registry: MetricsRegistry | None = None,
        record: bool = True,
        ring: bool = True,
    ):
        self.span = Span(name)
        self._registry = registry or REGISTRY
        self._record = record
        self._ring = ring

    def __enter__(self) -> Span:
        stack = _stack_var.get()
        if stack is None:
            stack = []
            _stack_var.set(stack)
        span = self.span
        span.request_id = get_request_id()
        span.trace_id = get_trace_id()
        span.span_id = new_span_id()
        if not stack:
            # a ROOT span parents to the cross-process caller (the span id
            # adopted from X-Pio-Parent-Span); children parent in-tree
            span.parent_id = get_parent_span()
        stack.append(span)
        span.start_ts = time.time()
        span.start_s = time.perf_counter()
        return span

    def __exit__(self, exc_type, exc, tb) -> None:
        self.span.duration_s = time.perf_counter() - self.span.start_s
        if exc is not None:
            self.span.error = f"{type(exc).__name__}: {exc}"
        stack = _stack_var.get() or []
        stack.pop()
        if stack:
            stack[-1].children.append(self.span)
        else:
            if self._ring:
                with _ring_lock:
                    _ring.append(self.span.to_dict())
            if self.span.trace_id:
                try:
                    # flatten the finished tree into cross-process fragments
                    # (bounded per-process store served at /spans.json)
                    _collect_fragments(self.span)
                except Exception:
                    pass  # telemetry must never break the traced block
        if self._record:
            self._registry.histogram(
                "pio_span_seconds",
                "Duration of named stages (trace spans)",
                labelnames=("span",),
                buckets=TRAIN_BUCKETS,
            ).labels(self.span.name).observe(self.span.duration_s)
        return None


def current_span() -> Span | None:
    stack = _stack_var.get()
    return stack[-1] if stack else None


def observe_span(
    name: str, seconds: float, registry: MetricsRegistry | None = None
) -> None:
    """Record an externally-timed duration as if it were a span (used by the
    JAX compile-time listener, which reports durations, not blocks)."""
    (registry or REGISTRY).histogram(
        "pio_span_seconds",
        "Duration of named stages (trace spans)",
        labelnames=("span",),
        buckets=TRAIN_BUCKETS,
    ).labels(name).observe(seconds)


def recent_traces(n: int = 20) -> list[dict[str, Any]]:
    """The most recent finished root spans, newest first."""
    with _ring_lock:
        items = list(_ring)
    return items[::-1][:n]


def clear_traces() -> None:
    with _ring_lock:
        _ring.clear()


_jax_listener_installed = False
_jax_listener_lock = threading.Lock()


def install_jax_compile_listener() -> bool:
    """Forward JAX compilation-event durations into the registry.

    Registers a ``jax.monitoring`` duration listener that records
    ``/jax/core/compile``-family events into ``pio_jax_compile_seconds`` —
    this is how a training run's stage breakdown separates XLA compile time
    from execute time — and counts them into ``pio_jax_compile_total`` so
    the device-efficiency layer (obs/device.py) can report cumulative
    compile activity next to its per-(fn, shapes) recompile attribution.
    Idempotent; returns False when the monitoring API is unavailable (the
    listener is additive-only, so failure is harmless).
    """
    global _jax_listener_installed
    with _jax_listener_lock:
        if _jax_listener_installed:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False
        if not hasattr(monitoring, "register_event_duration_secs_listener"):
            return False

        def _on_duration(event: str, duration: float, **kwargs) -> None:
            if "compile" not in event:
                return
            try:
                REGISTRY.histogram(
                    "pio_jax_compile_seconds",
                    "XLA compile time by jax monitoring event",
                    labelnames=("event",),
                    buckets=STAGE_BUCKETS,
                ).labels(event).observe(duration)
                REGISTRY.counter(
                    "pio_jax_compile_total",
                    "XLA compile events by jax monitoring event name",
                    labelnames=("event",),
                ).labels(event).inc()
            except Exception:
                pass  # telemetry must never break compilation

        try:
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _jax_listener_installed = True
        return True
