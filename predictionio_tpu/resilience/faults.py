"""Deterministic fault injection for chaos testing.

A seeded, plan-driven injector at two seams:

- ``remote.send`` / ``remote.response`` — the ``RemoteClient`` transport
  (connect/send phase and response phase), where injected connection
  resets, timeouts, and latency exercise the retry policy and circuit
  breaker exactly like a dying daemon would;
- ``batch_fn`` — the MicroBatcher dispatch, where injected errors exercise
  wave-failure isolation (solo retry).

Zero overhead when disabled: the seams do
``if faults.ACTIVE is not None: faults.ACTIVE.check(seam, label)`` — one
module-attribute read per call, no allocation, no plan parsing.

Plans are deterministic: rule matching is positional (``after`` skips the
first N matching calls, ``count`` bounds total firings) and probabilistic
rules draw from a ``random.Random(seed)``, so the same plan + seed + call
sequence injects the same faults — chaos tests assert exact outcomes, no
flakes.  Activate via the test API (:func:`install`/:func:`clear`) or the
environment::

    PIO_FAULT_PLAN='[{"seam": "remote.send", "kind": "connection_reset",
                      "match": "GET /v1", "count": 3}]'
    PIO_FAULT_PLAN=@/path/to/plan.json
    PIO_FAULT_SEED=7

See docs/robustness.md for the fault-plan cookbook.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence


class FaultInjected(Exception):
    """An injected application-level fault (kind="error")."""


#: kind -> exception factory; "latency"/"slow_response" sleep instead;
#: "corrupt" mutates bytes at data seams (FaultInjector.corrupt) and is
#: inert at raise/delay seams
_KIND_ERRORS: dict[str, Callable[[str], BaseException]] = {
    "error": FaultInjected,
    "connection_reset": ConnectionResetError,
    "connection_refused": ConnectionRefusedError,
    "timeout": TimeoutError,
}

_KINDS = frozenset(_KIND_ERRORS) | {"latency", "slow_response", "corrupt"}


@dataclass
class FaultRule:
    """One line of a fault plan.

    ``seam`` names the injection point; ``match`` is a substring filter on
    the seam's call label (e.g. ``"GET /v1/apps"``); ``after`` skips the
    first N matching calls; ``count`` caps total firings (None =
    unlimited); ``probability`` gates each firing through the seeded RNG;
    ``latency_s`` is the injected delay for latency kinds (which fire and
    then let the call proceed).
    """

    seam: str
    kind: str
    match: str = ""
    after: int = 0
    count: int | None = None
    probability: float = 1.0
    latency_s: float = 0.0
    message: str = "injected fault"
    # bookkeeping (not part of the plan wire format)
    seen: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; have {sorted(_KINDS)}"
            )


class FaultInjector:
    """Evaluate a plan of :class:`FaultRule` at each instrumented seam."""

    def __init__(
        self,
        rules: Sequence[FaultRule],
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.rules = list(rules)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._lock = threading.Lock()

    def check(self, seam: str, label: str = "") -> None:
        """Raise/delay per the plan for one call at ``seam``.  Rules are
        evaluated in order; the first *raising* rule wins, latency rules
        stack with whatever follows."""
        for r in self.rules:
            if r.seam != seam or (r.match and r.match not in label):
                continue
            if r.kind == "corrupt":
                continue  # data-mutation rules only fire through corrupt()
            with self._lock:
                n = r.seen
                r.seen += 1
                if n < r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.probability < 1.0 and self._rng.random() >= r.probability:
                    continue
                r.fired += 1
            if r.kind in ("latency", "slow_response"):
                self._sleep(r.latency_s)
                continue
            raise _KIND_ERRORS[r.kind](
                f"{r.message} [{r.kind} @ {seam} {label}]".strip()
            )

    def latency(self, seam: str, label: str = "") -> float:
        """Latency-kind rules as a QUERY: return the matching rules' total
        injected delay instead of sleeping it, for seams that fold the
        delay into their own clock — the per-shard settle measurement
        (``placement.settle_shards``) defers one device's observed
        readiness rather than stalling the poll over every device.  Same
        after/count/probability bookkeeping as :meth:`check`; raising
        kinds never fire here."""
        total = 0.0
        for r in self.rules:
            if r.seam != seam or r.kind not in ("latency", "slow_response"):
                continue
            if r.match and r.match not in label:
                continue
            with self._lock:
                n = r.seen
                r.seen += 1
                if n < r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.probability < 1.0 and self._rng.random() >= r.probability:
                    continue
                r.fired += 1
            total += r.latency_s
        return total

    def corrupt(self, seam: str, label: str, data: bytes) -> bytes:
        """Data-seam injection: deterministically flip bytes when a
        ``kind="corrupt"`` rule matches (same after/count/probability
        bookkeeping as :meth:`check`).  Used by checksum-verified readers
        (lifecycle generation store) to prove corrupt blobs are refused —
        the mutation is a bit-flip per 1 KiB page, so any real checksum
        catches it."""
        for r in self.rules:
            if r.seam != seam or r.kind != "corrupt":
                continue
            if r.match and r.match not in label:
                continue
            with self._lock:
                n = r.seen
                r.seen += 1
                if n < r.after:
                    continue
                if r.count is not None and r.fired >= r.count:
                    continue
                if r.probability < 1.0 and self._rng.random() >= r.probability:
                    continue
                r.fired += 1
            if not data:
                continue
            out = bytearray(data)
            for i in range(0, len(out), 1024):
                out[i] ^= 0xFF
            return bytes(out)
        return data

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return [
                {
                    "seam": r.seam,
                    "kind": r.kind,
                    "match": r.match,
                    "seen": r.seen,
                    "fired": r.fired,
                }
                for r in self.rules
            ]


#: the process-wide injector; None (the overwhelmingly common case) makes
#: every seam a single attribute check
ACTIVE: FaultInjector | None = None


def install(
    rules: Sequence[FaultRule | dict], seed: int = 0, **kwargs: Any
) -> FaultInjector:
    """Install a plan process-wide (test API).  Dicts are FaultRule
    kwargs.  Returns the injector so tests can read firing counts."""
    global ACTIVE
    parsed = [r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules]
    ACTIVE = FaultInjector(parsed, seed=seed, **kwargs)
    return ACTIVE


def clear() -> None:
    global ACTIVE
    ACTIVE = None


def load_env_plan(env: dict[str, str] | None = None) -> FaultInjector | None:
    """Install a plan from ``PIO_FAULT_PLAN`` (inline JSON or ``@path``)
    and ``PIO_FAULT_SEED``.  Called once at import; returns the injector
    (or None).  A malformed plan raises — silently ignoring a chaos plan
    would fake a green chaos run."""
    e = env if env is not None else os.environ
    raw = e.get("PIO_FAULT_PLAN")
    if not raw:
        return None
    if raw.startswith("@"):
        with open(raw[1:], "r", encoding="utf-8") as f:
            raw = f.read()
    plan = json.loads(raw)
    if not isinstance(plan, list):
        raise ValueError("PIO_FAULT_PLAN must be a JSON array of rules")
    return install(plan, seed=int(e.get("PIO_FAULT_SEED", "0")))


load_env_plan()
