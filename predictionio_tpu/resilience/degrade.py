"""Degraded-mode marking: answer worse, loudly, instead of failing.

An engine with live event-store reads on its hot path (ecommerce
seen-filtering / recent-items supplement) can still serve a model-only
answer when the store is unreachable or out of budget.  That fallback must
be *visible*: unmarked degradation looks identical to health until someone
notices recommendations repeating items users already bought.

:func:`mark_degraded` is what a fallback site calls.  It increments
``pio_degraded_total{reason}``, tags the flight-recorder entry, and — when
a :func:`degraded_scope` is open — records the reason so the serving layer
can stamp the response (``X-Pio-Degraded`` header).  Scopes are contextvar
based, so they work on request threads, inside ``run_in_executor``
handlers (via ``copy_context``), and on the MicroBatcher worker (which
opens one scope per wave).
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Iterator

from predictionio_tpu.obs.flight import annotate
from predictionio_tpu.obs.metrics import REGISTRY

_degraded_var: contextvars.ContextVar[list[str] | None] = (
    contextvars.ContextVar("pio_degraded", default=None)
)

_m_degraded = REGISTRY.counter(
    "pio_degraded_total",
    "Requests answered in degraded (fallback) mode, by reason",
    labelnames=("reason",),
)


def mark_degraded(reason: str) -> None:
    """Record that the current operation fell back to a degraded answer."""
    _m_degraded.labels(reason).inc()
    annotate(degraded=reason)
    reasons = _degraded_var.get()
    if reasons is not None and reason not in reasons:
        reasons.append(reason)


def current_degraded() -> list[str]:
    """Reasons recorded in the innermost open scope (empty when none)."""
    return list(_degraded_var.get() or ())


@contextlib.contextmanager
def degraded_scope() -> Iterator[list[str]]:
    """Collect degradation reasons for a block; yields the live list."""
    reasons: list[str] = []
    token = _degraded_var.set(reasons)
    try:
        yield reasons
    finally:
        _degraded_var.reset(token)
