"""Admission control: a per-server in-flight cap.

Unbounded concurrency is how overload becomes collapse: every accepted
request adds queueing delay for all of them until everything times out at
once.  An :class:`AdmissionController` bounds in-flight (non-probe)
requests; past the cap the front ends answer ``503 + Retry-After``
immediately — cheap to produce, honest to the client, and the admitted
requests keep their latency.

``try_acquire``/``release`` are O(1) under one lock; the in-flight count
is exported as ``pio_inflight_requests`` and sheds as
``pio_shed_total{reason="inflight"}``.
"""

from __future__ import annotations

from predictionio_tpu.obs.contention import ContendedLock
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry

#: one shed counter family shared by every shedding site (admission cap,
#: microbatch queue bound), labeled by reason
def shed_counter(registry: MetricsRegistry | None = None):
    return (registry or REGISTRY).counter(
        "pio_shed_total",
        "Requests shed with 503 + Retry-After instead of queuing, by reason",
        labelnames=("reason",),
    )


class AdmissionController:
    """Bounded in-flight request counter for one server."""

    def __init__(
        self,
        max_inflight: int,
        retry_after_s: float = 1.0,
        registry: MetricsRegistry | None = None,
        reason: str = "inflight",
    ):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self.retry_after_s = retry_after_s
        reg = registry or REGISTRY
        # every admitted request acquires twice (acquire + release); under
        # concurrency this is a front-end hot lock, so blocked
        # acquisitions are metered (pio_lock_wait_seconds{lock="admission"})
        self._lock = ContendedLock("admission", registry=reg)
        self._inflight = 0
        # ``reason`` distinguishes controllers sharing one registry
        # (single-VM deploys run the serving cap AND the event server's
        # write gate): without the label both would write one gauge and
        # ingest bursts would masquerade as serving load
        self._m_inflight = reg.gauge(
            "pio_inflight_requests",
            "Requests currently admitted and executing, by admission gate",
            labelnames=("reason",),
        ).labels(reason)
        self._m_shed = shed_counter(reg).labels(reason)

    def try_acquire(self) -> bool:
        with self._lock:
            if self._inflight >= self.max_inflight:
                self._m_shed.inc()
                return False
            self._inflight += 1
            self._m_inflight.set(self._inflight)
            return True

    def release(self) -> None:
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            self._m_inflight.set(self._inflight)

    @property
    def inflight(self) -> int:
        return self._inflight
