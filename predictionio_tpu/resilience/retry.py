"""Retry policy: bounded attempts, decorrelated-jitter backoff, and a
retry *budget* so retries can't amplify an outage.

The ad-hoc shape this replaces: ``RemoteClient`` hard-coded exactly one
blind retry.  A :class:`RetryPolicy` makes the attempt count, backoff
curve, and jitter explicit and testable; a :class:`RetryBudget` (token
bucket fed by successful first attempts) caps the *fleet-level* retry rate
— when a daemon is down, unbudgeted exponential-backoff retries from every
serving thread are a synchronized thundering herd at exactly the moment
the daemon restarts.

Backoff uses "decorrelated jitter" (the AWS Architecture Blog variant):
``sleep = min(cap, uniform(base, prev * 3))`` — spreads retries across the
window instead of clustering at powers of two.  The RNG is injectable so
tests are deterministic.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """How many times to try and how long to wait between tries.

    ``max_attempts`` counts the first try (2 == one retry, the legacy
    RemoteClient behavior).  ``base_backoff_s``/``max_backoff_s`` bound the
    decorrelated-jitter sleep; attempt 0 never sleeps.
    """

    max_attempts: int = 2
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")

    def backoff_s(
        self, prev_backoff_s: float, rng: random.Random
    ) -> float:
        """Next sleep given the previous one (0.0 before the first retry)."""
        prev = max(prev_backoff_s, self.base_backoff_s)
        return min(
            self.max_backoff_s, rng.uniform(self.base_backoff_s, prev * 3.0)
        )


#: a policy that never retries (breaker-only operation)
NO_RETRY = RetryPolicy(max_attempts=1)


class RetryBudget:
    """Token bucket limiting retries to a fraction of successful traffic.

    Every completed call deposits ``deposit_per_call`` (capped at ``cap``);
    every retry withdraws 1.0.  With the defaults, sustained retries are
    limited to ~10% of call volume — one slow daemon degrades retries to a
    trickle instead of doubling its own load.  Starts full so cold-start
    blips (daemon restarting as the server boots) still get retried.
    """

    def __init__(self, cap: float = 10.0, deposit_per_call: float = 0.1):
        self.cap = float(cap)
        self.deposit_per_call = float(deposit_per_call)
        self._lock = threading.Lock()
        self._tokens = self.cap

    def record_call(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.deposit_per_call, self.cap)

    def try_spend(self) -> bool:
        """True when a retry may proceed (a token was available)."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        return self._tokens
