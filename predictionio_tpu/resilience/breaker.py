"""Circuit breakers: fail fast when a dependency is down.

Per-endpoint closed→open→half-open state machine (the Nygard pattern).
Closed counts consecutive failures; at ``failure_threshold`` it opens and
every call is rejected in ~0 ms (a :class:`CircuitOpen` with a
``retry_after_s`` hint) instead of paying a connect timeout.  After
``reset_timeout_s`` the breaker half-opens and admits ``half_open_max``
trial calls; one success closes it, one failure re-opens it and restarts
the clock.

State is exported as a ``pio_breaker_state{endpoint=...}`` gauge
(0 = closed, 1 = half-open, 2 = open) on the process registry, folded into
``/readyz`` (prediction server), ``/slo.json``, and ``pio status --url``.

``_now`` is module-level so tests drive transitions with a frozen clock
instead of real sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from predictionio_tpu.obs.metrics import REGISTRY

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"

#: gauge encoding of the states (ordered by "how broken")
BREAKER_STATES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def _now() -> float:
    """Monotonic clock — module-level so tests can freeze it."""
    return time.monotonic()


class CircuitOpen(Exception):
    """Call rejected because the breaker is open (or half-open with its
    trial slots taken).  ``retry_after_s`` hints when to try again."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """One endpoint's breaker.  Thread-safe: every transition happens
    inline under one lock (and is mirrored to the state gauge, which locks
    internally)."""

    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        reset_timeout_s: float = 5.0,
        half_open_max: int = 1,
        registry=None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._trials = 0  # in-flight trial calls while half-open
        self._opened_total = 0
        reg = registry or REGISTRY
        self._gauge = reg.gauge(
            "pio_breaker_state",
            "Circuit breaker state by endpoint (0 closed, 1 half-open, 2 open)",
            labelnames=("endpoint",),
        ).labels(name)
        self._m_rejected = reg.counter(
            "pio_breaker_rejected_total",
            "Calls rejected in ~0 ms because the breaker was not closed",
            labelnames=("endpoint",),
        ).labels(name)
        self._gauge.set(BREAKER_STATES[CLOSED])

    def allow(self) -> bool:
        """True when a call may proceed.  Half-open trial slots are
        consumed here and released by record_success/record_failure."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if _now() - self._opened_at < self.reset_timeout_s:
                    self._m_rejected.inc()
                    return False
                self._state = HALF_OPEN
                self._trials = 0
                self._gauge.set(BREAKER_STATES[HALF_OPEN])
            # HALF_OPEN: admit up to half_open_max concurrent trials
            if self._trials < self.half_open_max:
                self._trials += 1
                return True
            self._m_rejected.inc()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == HALF_OPEN:
                self._trials = max(self._trials - 1, 0)
                self._state = CLOSED
                self._gauge.set(BREAKER_STATES[CLOSED])

    def reset(self) -> None:
        """Force-close on out-of-band positive proof of health (the fleet
        prober's successful /readyz probe): the reset window exists to
        pace blind retries, not to overrule an actual observed answer —
        without this a revived replica can sit unroutable (breaker open)
        while its /readyz already says ready."""
        with self._lock:
            self._failures = 0
            self._trials = 0
            if self._state != CLOSED:
                self._state = CLOSED
                self._gauge.set(BREAKER_STATES[CLOSED])

    def release_trial(self) -> None:
        """A half-open trial ended with neither a success nor an endpoint
        failure (e.g. the caller's deadline ran out mid-call): free the
        slot so recovery probing can continue.  Without this, an abandoned
        trial would wedge the breaker half-open with no slots forever."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._trials = max(self._trials - 1, 0)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the trial failed: straight back to open, clock restarts
                self._trials = max(self._trials - 1, 0)
                self._opened_at = _now()
                self._opened_total += 1
                self._state = OPEN
                self._gauge.set(BREAKER_STATES[OPEN])
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._opened_at = _now()
                self._opened_total += 1
                self._state = OPEN
                self._gauge.set(BREAKER_STATES[OPEN])

    def guard(self, what: str = "call") -> None:
        """Raise :class:`CircuitOpen` when the breaker rejects the call."""
        if not self.allow():
            retry_after = self.retry_after_s()
            raise CircuitOpen(
                f"{what} rejected: circuit {self.name!r} is {self.state} "
                f"(retry in ~{retry_after:.1f}s)",
                retry_after_s=retry_after,
            )

    # -- introspection -------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            # an expired open window *reads* as half-open so /readyz and
            # pio status report recoverability without waiting for traffic
            if (
                self._state == OPEN
                and _now() - self._opened_at >= self.reset_timeout_s
            ):
                return HALF_OPEN
            return self._state

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(self.reset_timeout_s - (_now() - self._opened_at), 0.0)

    def snapshot(self) -> dict[str, Any]:
        state = self.state
        with self._lock:
            return {
                "state": state,
                "failures": self._failures,
                "failure_threshold": self.failure_threshold,
                "reset_timeout_s": self.reset_timeout_s,
                "opened_total": self._opened_total,
            }


#: process-wide breakers by endpoint name, so every RemoteClient pointed at
#: the same daemon shares one view of its health
_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()


def get_breaker(name: str, **kwargs: Any) -> CircuitBreaker:
    """Get-or-create the process-wide breaker for ``name``.  First creation
    fixes the parameters; later callers share the instance."""
    with _BREAKERS_LOCK:
        br = _BREAKERS.get(name)
        if br is None:
            br = CircuitBreaker(name, **kwargs)
            _BREAKERS[name] = br
        return br


def breaker_states() -> dict[str, dict[str, Any]]:
    """Snapshot of every registered breaker (for /slo.json + pio status)."""
    with _BREAKERS_LOCK:
        items = list(_BREAKERS.items())
    return {name: br.snapshot() for name, br in items}


def reset_breakers() -> None:
    """Drop all registered breakers (test isolation)."""
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
