"""Resilience layer: deadlines, load shedding, circuit breakers, degraded
serving, and deterministic fault injection.

PRs 1-4 made failures *visible* (metrics, flight recorder, SLO burn rates,
drift); this package makes the system *fail well*.  The reference leaned on
Spark's task retry/speculation for fault tolerance (SURVEY.md §4) — the
TPU-native serving path needs its own primitives, the ones production
serving systems treat as first-class (TensorFlow's explicit fault-tolerance
design, arxiv 1605.08695; DrJAX's bounded composable execution, arxiv
2403.07128):

- :mod:`deadline` — per-request time budgets bound to the request
  contextvars (``X-Pio-Deadline``), enforced at admission, before each
  MicroBatcher wave, and capping every outbound storage call;
- :mod:`admission` — bounded in-flight request cap so overload sheds with
  ``503 + Retry-After`` instead of collapsing;
- :mod:`retry` — bounded retry policy with decorrelated-jitter backoff and
  a retry budget (no retry storms);
- :mod:`breaker` — closed→open→half-open circuit breakers per daemon
  endpoint, exported as ``pio_breaker_state`` gauges;
- :mod:`degrade` — mark responses/metrics degraded when an engine falls
  back to model-only serving instead of erroring;
- :mod:`faults` — a seeded, plan-driven fault injector at the RemoteClient
  transport seam and the MicroBatcher ``batch_fn`` seam (zero overhead when
  disabled) powering the deterministic chaos suite.

See docs/robustness.md for semantics and the fault-plan cookbook.
"""

from predictionio_tpu.resilience.admission import AdmissionController  # noqa: F401
from predictionio_tpu.resilience.breaker import (  # noqa: F401
    BREAKER_STATES,
    CircuitBreaker,
    CircuitOpen,
    breaker_states,
    get_breaker,
    reset_breakers,
)
from predictionio_tpu.resilience.deadline import (  # noqa: F401
    DEADLINE_HEADER,
    DeadlineExceeded,
    deadline_scope,
    get_deadline,
    remaining,
)
from predictionio_tpu.resilience.degrade import (  # noqa: F401
    current_degraded,
    degraded_scope,
    mark_degraded,
)
from predictionio_tpu.resilience.faults import (  # noqa: F401
    FaultInjected,
    FaultInjector,
    FaultRule,
)
from predictionio_tpu.resilience.retry import (  # noqa: F401
    RetryBudget,
    RetryPolicy,
)


class LoadShed(Exception):
    """Request rejected by admission control (bounded queue / in-flight
    cap).  Maps to ``503`` with a ``Retry-After`` header so well-behaved
    clients back off instead of hammering a saturated server."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s
