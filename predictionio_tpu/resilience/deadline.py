"""Deadline propagation: a per-request time budget that travels with the
request context.

The failure this kills: an unreachable storage daemon used to stall every
serving thread for the full 30 s ``RemoteClient`` timeout while the client
had long since hung up.  With a deadline bound at admission (from the
``X-Pio-Deadline`` header or the server's default budget), every layer can
ask :func:`remaining` and stop doing work nobody will consume:

- the HTTP front ends reject already-expired requests at admission;
- the MicroBatcher resolves expired queued items with
  :class:`DeadlineExceeded` instead of wasting device time on them;
- ``RemoteClient`` caps each socket timeout to the remaining budget.

The deadline is stored as an *absolute* monotonic instant in a contextvar,
so nested calls all count down the same budget (gRPC deadline semantics,
not per-hop timeouts).  The wire format is *relative* seconds (clocks are
not shared across hosts).  ``_now`` is module-level so tests can freeze it.
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Iterator

#: request header carrying the remaining budget in (fractional) seconds
DEADLINE_HEADER = "X-Pio-Deadline"

_deadline_var: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "pio_deadline", default=None
)


def _now() -> float:
    """Monotonic clock — module-level so tests can freeze it."""
    return time.monotonic()


class DeadlineExceeded(Exception):
    """The request's time budget ran out before the work completed.
    Maps to HTTP 504 on the serving surface."""


def bind_deadline(absolute: float | None) -> contextvars.Token:
    """Bind an absolute monotonic deadline to the current context."""
    return _deadline_var.set(absolute)


def set_deadline(budget_s: float) -> contextvars.Token:
    """Bind a deadline ``budget_s`` seconds from now."""
    return bind_deadline(_now() + budget_s)


def reset_deadline(token: contextvars.Token) -> None:
    _deadline_var.reset(token)


def get_deadline() -> float | None:
    """The absolute monotonic deadline bound to this context, or None."""
    return _deadline_var.get()


def remaining() -> float | None:
    """Seconds of budget left (may be <= 0), or None when no deadline."""
    dl = _deadline_var.get()
    return None if dl is None else dl - _now()


def expired() -> bool:
    dl = _deadline_var.get()
    return dl is not None and dl <= _now()


def check(what: str = "request") -> None:
    """Raise :class:`DeadlineExceeded` when the bound deadline has passed."""
    rem = remaining()
    if rem is not None and rem <= 0:
        raise DeadlineExceeded(
            f"{what} deadline exceeded ({-rem * 1000.0:.0f} ms past budget)"
        )


def parse_budget(value: str | None) -> float | None:
    """Parse a wire budget (seconds, e.g. ``"0.25"``) into a float.
    Malformed or non-positive-insane values yield None — a client typo must
    not 500 the request, it just serves without a deadline."""
    if not value:
        return None
    try:
        budget = float(value)
    except ValueError:
        return None
    if budget != budget or budget in (float("inf"), float("-inf")):
        return None
    return budget


@contextlib.contextmanager
def deadline_scope(
    budget_s: float | None = None, absolute: float | None = None
) -> Iterator[None]:
    """Bind a deadline for the duration of a block (no-op when both are
    None).  ``absolute`` wins when given — the MicroBatcher worker re-binds
    a wave's earliest captured deadline this way."""
    if budget_s is None and absolute is None:
        yield
        return
    token = (
        bind_deadline(absolute) if absolute is not None else set_deadline(budget_s)
    )
    try:
        yield
    finally:
        reset_deadline(token)
