"""FastEvalEngine: per-prefix memoization for hyperparameter sweeps.

Mirrors controller/FastEvalEngine.scala:46-345: when evaluating an
engine-params list, many variants share a prefix of the pipeline
(same datasource -> same eval sets; same +preparator -> same prepared data;
same +algorithm params -> same trained models).  Caching on the serialized
params prefix makes an N-variant sweep cost ~1 datasource read + P prepares +
A trains instead of N of each.
"""

from __future__ import annotations

import json
import os
import tempfile
from collections import OrderedDict
from typing import Any, Sequence

from predictionio_tpu.core.base import EngineContext
from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.utils.params import params_to_dict
from predictionio_tpu.utils.registry import doer


def _key(*parts: Any) -> str:
    return json.dumps(parts, sort_keys=True, default=str)


class SpillingModelCache:
    """Bounded trained-model cache: at most ``max_live`` entries stay in
    RAM; older entries spill to disk via core.persistence and reload on hit.

    The reference's FastEvalEngine holds lazy Spark handles, so caching every
    params-prefix is free (FastEvalEngine.scala:46-345).  Here entries are
    materialized factor/embedding matrices — an unbounded dict OOMs the host
    on a large sweep at ML-20M scale, so the LRU spills evictions through
    ``serialize_models`` (device arrays come back as host numpy, which the
    eval path accepts anywhere a trained model is used).
    """

    def __init__(self, max_live: int | None = None):
        if max_live is None:
            max_live = int(os.environ.get("PIO_FAST_EVAL_MAX_LIVE", "2"))
        self.max_live = max(max_live, 1)
        self._live: OrderedDict[str, list] = OrderedDict()
        self._spilled: dict[str, str] = {}  # key -> file path
        self._dir: tempfile.TemporaryDirectory | None = None
        self.reload_count = 0

    def __contains__(self, key: str) -> bool:
        return key in self._live or key in self._spilled

    def __len__(self) -> int:
        return len(self._live) + len(self._spilled)

    @property
    def live_count(self) -> int:
        return len(self._live)

    def get(self, key: str) -> list:
        if key in self._live:
            self._live.move_to_end(key)
            return self._live[key]
        from predictionio_tpu.core.persistence import deserialize_models

        path = self._spilled.pop(key)
        with open(path, "rb") as f:
            models = deserialize_models(f.read())
        os.unlink(path)  # a later re-spill rewrites it; never orphan blobs
        self.reload_count += 1
        self.put(key, models)
        return models

    def put(self, key: str, models: list) -> None:
        self._live[key] = models
        self._live.move_to_end(key)
        while len(self._live) > self.max_live:
            self._spill(*self._live.popitem(last=False))

    def _spill(self, key: str, models: list) -> None:
        import hashlib

        from predictionio_tpu.core.persistence import serialize_models

        if self._dir is None:
            self._dir = tempfile.TemporaryDirectory(prefix="pio_fasteval_")
        # deterministic per-key name: a spill->reload->re-spill cycle
        # overwrites the same file instead of accumulating orphans
        digest = hashlib.sha1(key.encode()).hexdigest()[:20]
        path = os.path.join(self._dir.name, f"spill_{digest}.pkl")
        with open(path, "wb") as f:
            f.write(serialize_models(models))
        self._spilled[key] = path


class FastEvalEngine(Engine):
    """Engine whose eval() memoizes datasource/preparator/algorithm prefixes."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ds_cache: dict[str, Any] = {}
        self._prep_cache: dict[str, Any] = {}
        # trained models: bounded LRU that spills evictions to disk so a
        # large sweep runs in bounded RSS (see SpillingModelCache)
        self._train_cache = SpillingModelCache()
        # hit counters exposed for tests (FastEvalEngineTest counts cache use)
        self.counts = {"datasource": 0, "preparator": 0, "train": 0}

    @classmethod
    def from_engine(cls, engine: Engine) -> "FastEvalEngine":
        return cls(
            engine.datasource_classes,
            engine.preparator_classes,
            engine.algorithm_classes,
            engine.serving_classes,
        )

    def _eval_sets(self, ctx: EngineContext, params: EngineParams):
        k = _key(params.datasource[0], params_to_dict(params.datasource[1]))
        if k not in self._ds_cache:
            self.counts["datasource"] += 1
            ds = doer(
                self.datasource_classes[params.datasource[0]], params.datasource[1]
            )
            self._ds_cache[k] = ds.read_eval(ctx)
        return k, self._ds_cache[k]

    def _prepared(self, ctx: EngineContext, params: EngineParams):
        ds_key, eval_sets = self._eval_sets(ctx, params)
        k = _key(ds_key, params.preparator[0], params_to_dict(params.preparator[1]))
        if k not in self._prep_cache:
            self.counts["preparator"] += 1
            prep = doer(
                self.preparator_classes[params.preparator[0]], params.preparator[1]
            )
            self._prep_cache[k] = [
                prep.prepare(ctx, td) for td, _, _ in eval_sets
            ]
        return k, eval_sets, self._prep_cache[k]

    def _models(self, ctx: EngineContext, params: EngineParams):
        prep_key, eval_sets, pds = self._prepared(ctx, params)
        per_algo_models = []
        for name, algo_params in params.algorithms:
            k = _key(prep_key, name, params_to_dict(algo_params))
            if k not in self._train_cache:
                self.counts["train"] += 1
                algo = doer(self.algorithm_classes[name], algo_params)
                self._train_cache.put(k, [algo.train(ctx, pd) for pd in pds])
            per_algo_models.append(self._train_cache.get(k))
        return eval_sets, per_algo_models

    def eval(self, ctx: EngineContext, params: EngineParams):
        from predictionio_tpu.core.engine import serve_eval_fold

        eval_sets, per_algo_models = self._models(ctx, params)
        algos = [
            doer(self.algorithm_classes[name], p) for name, p in params.algorithms
        ]
        serving = doer(
            self.serving_classes[params.serving[0]], params.serving[1]
        )
        results = []
        for fold, (td, eval_info, qa_pairs) in enumerate(eval_sets):
            fold_models = [ms[fold] for ms in per_algo_models]
            results.append(
                (eval_info, serve_eval_fold(algos, fold_models, serving, qa_pairs))
            )
        return results
