"""FastEvalEngine: per-prefix memoization for hyperparameter sweeps.

Mirrors controller/FastEvalEngine.scala:46-345: when evaluating an
engine-params list, many variants share a prefix of the pipeline
(same datasource -> same eval sets; same +preparator -> same prepared data;
same +algorithm params -> same trained models).  Caching on the serialized
params prefix makes an N-variant sweep cost ~1 datasource read + P prepares +
A trains instead of N of each.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

from predictionio_tpu.core.base import EngineContext
from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.utils.params import params_to_dict
from predictionio_tpu.utils.registry import doer


def _key(*parts: Any) -> str:
    return json.dumps(parts, sort_keys=True, default=str)


class FastEvalEngine(Engine):
    """Engine whose eval() memoizes datasource/preparator/algorithm prefixes."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._ds_cache: dict[str, Any] = {}
        self._prep_cache: dict[str, Any] = {}
        self._train_cache: dict[str, Any] = {}
        # hit counters exposed for tests (FastEvalEngineTest counts cache use)
        self.counts = {"datasource": 0, "preparator": 0, "train": 0}

    @classmethod
    def from_engine(cls, engine: Engine) -> "FastEvalEngine":
        return cls(
            engine.datasource_classes,
            engine.preparator_classes,
            engine.algorithm_classes,
            engine.serving_classes,
        )

    def _eval_sets(self, ctx: EngineContext, params: EngineParams):
        k = _key(params.datasource[0], params_to_dict(params.datasource[1]))
        if k not in self._ds_cache:
            self.counts["datasource"] += 1
            ds = doer(
                self.datasource_classes[params.datasource[0]], params.datasource[1]
            )
            self._ds_cache[k] = ds.read_eval(ctx)
        return k, self._ds_cache[k]

    def _prepared(self, ctx: EngineContext, params: EngineParams):
        ds_key, eval_sets = self._eval_sets(ctx, params)
        k = _key(ds_key, params.preparator[0], params_to_dict(params.preparator[1]))
        if k not in self._prep_cache:
            self.counts["preparator"] += 1
            prep = doer(
                self.preparator_classes[params.preparator[0]], params.preparator[1]
            )
            self._prep_cache[k] = [
                prep.prepare(ctx, td) for td, _, _ in eval_sets
            ]
        return k, eval_sets, self._prep_cache[k]

    def _models(self, ctx: EngineContext, params: EngineParams):
        prep_key, eval_sets, pds = self._prepared(ctx, params)
        per_algo_models = []
        for name, algo_params in params.algorithms:
            k = _key(prep_key, name, params_to_dict(algo_params))
            if k not in self._train_cache:
                self.counts["train"] += 1
                algo = doer(self.algorithm_classes[name], algo_params)
                self._train_cache[k] = [algo.train(ctx, pd) for pd in pds]
            per_algo_models.append(self._train_cache[k])
        return eval_sets, per_algo_models

    def eval(self, ctx: EngineContext, params: EngineParams):
        from predictionio_tpu.core.engine import serve_eval_fold

        eval_sets, per_algo_models = self._models(ctx, params)
        algos = [
            doer(self.algorithm_classes[name], p) for name, p in params.algorithms
        ]
        serving = doer(
            self.serving_classes[params.serving[0]], params.serving[1]
        )
        results = []
        for fold, (td, eval_info, qa_pairs) in enumerate(eval_sets):
            fold_models = [ms[fold] for ms in per_algo_models]
            results.append(
                (eval_info, serve_eval_fold(algos, fold_models, serving, qa_pairs))
            )
        return results
