"""MetricEvaluator: score an engine-params sweep and pick the best.

Mirrors controller/MetricEvaluator.scala:185: for each EngineParams in the
sweep, run the engine's eval pipeline, compute the primary metric (+ any
additional metrics), track the best by the metric's ordering, and render
one-liner / HTML / JSON results for the EvaluationInstance record and the
dashboard.
"""

from __future__ import annotations

import html as html_mod
import json
import logging
from dataclasses import dataclass
from typing import Any, Sequence

from predictionio_tpu.core.base import EngineContext
from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.core.metric import Metric
from predictionio_tpu.utils.params import params_to_dict

log = logging.getLogger("predictionio_tpu.eval")


@dataclass
class EvaluationRecord:
    engine_params: EngineParams
    score: float
    other_scores: dict[str, float]


@dataclass
class EvaluationResult:
    """All sweep records + the winner (MetricEvaluatorResult:64)."""

    metric_header: str
    other_headers: list[str]
    records: list[EvaluationRecord]
    best_idx: int

    @property
    def best(self) -> EvaluationRecord:
        return self.records[self.best_idx]

    def one_liner(self) -> str:
        b = self.best
        return (
            f"[{self.metric_header}] best score: {b.score:.6f} "
            f"(params set {self.best_idx + 1} of {len(self.records)})"
        )

    def _params_dict(self, ep: EngineParams) -> dict:
        return {
            "datasource": {ep.datasource[0]: params_to_dict(ep.datasource[1])},
            "preparator": {ep.preparator[0]: params_to_dict(ep.preparator[1])},
            "algorithms": [{n: params_to_dict(p)} for n, p in ep.algorithms],
            "serving": {ep.serving[0]: params_to_dict(ep.serving[1])},
        }

    def to_json(self) -> str:
        return json.dumps(
            {
                "metric": self.metric_header,
                "otherMetrics": self.other_headers,
                "bestIdx": self.best_idx,
                "bestScore": self.best.score,
                "records": [
                    {
                        "score": r.score,
                        "otherScores": r.other_scores,
                        "engineParams": self._params_dict(r.engine_params),
                    }
                    for r in self.records
                ],
            },
            default=str,
        )

    def to_html(self) -> str:
        rows = "".join(
            f"<tr{' class=best' if i == self.best_idx else ''}>"
            f"<td>{i + 1}</td><td>{r.score:.6f}</td>"
            f"<td>{''.join(f'{k}={v:.6f} ' for k, v in r.other_scores.items())}</td>"
            f"<td><pre>{html_mod.escape(json.dumps(self._params_dict(r.engine_params), indent=1, default=str))}</pre></td></tr>"
            for i, r in enumerate(self.records)
        )
        return (
            "<table border=1><tr><th>#</th>"
            f"<th>{html_mod.escape(self.metric_header)}</th><th>other metrics</th>"
            f"<th>engine params</th></tr>{rows}</table>"
        )


class MetricEvaluator:
    """Evaluate each EngineParams with the engine and a primary metric."""

    def __init__(
        self, metric: Metric, other_metrics: Sequence[Metric] = ()
    ):
        self.metric = metric
        self.other_metrics = list(other_metrics)

    def evaluate(
        self,
        ctx: EngineContext,
        engine: Engine,
        engine_params_list: Sequence[EngineParams],
    ) -> EvaluationResult:
        from predictionio_tpu.obs.tracing import trace

        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        records: list[EvaluationRecord] = []
        best_idx = 0
        for i, ep in enumerate(engine_params_list):
            # one span per params candidate: a sweep's cost decomposes into
            # engine.eval (train+predict per fold) vs metric calculation
            with trace("eval.engine_params"):
                fold_data = engine.eval(ctx, ep)
            with trace("eval.metric.calculate"):
                score = self.metric.calculate(fold_data)
                others = {
                    m.header(): m.calculate(fold_data)
                    for m in self.other_metrics
                }
            records.append(EvaluationRecord(ep, score, others))
            log.info(
                "eval %d/%d: %s = %s",
                i + 1,
                len(engine_params_list),
                self.metric.header(),
                score,
            )
            if self.metric.comparison(score, records[best_idx].score) > 0:
                best_idx = i
        return EvaluationResult(
            metric_header=self.metric.header(),
            other_headers=[m.header() for m in self.other_metrics],
            records=records,
            best_idx=best_idx,
        )
