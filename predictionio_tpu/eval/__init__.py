from predictionio_tpu.eval.evaluator import (
    EvaluationResult,
    MetricEvaluator,
)
from predictionio_tpu.eval.fast_eval import FastEvalEngine

__all__ = ["EvaluationResult", "FastEvalEngine", "MetricEvaluator"]
