"""Evaluation binding: engine + engine-params sweep + metrics.

The controller/Evaluation.scala:34-124 analog: an ``Evaluation`` names the
engine (factory), the list of EngineParams to sweep, and the metric(s); the
CLI's ``eval`` verb imports one by path
(``pkg.module:evaluation_object``) and hands it to ``run_evaluation`` —
the reference's `pio eval <Evaluation> <EngineParamsGenerator>` collapses to
one object because params generators are plain lists/functions here
(EngineParamsGenerator.scala:30).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from predictionio_tpu.core.engine import Engine, EngineParams
from predictionio_tpu.core.metric import Metric


@dataclass
class Evaluation:
    """Bind an engine factory to a params sweep and metrics."""

    engine_factory: Callable[[], Engine]
    engine_params_list: Sequence[EngineParams] | Callable[[], Sequence[EngineParams]]
    metric: Metric
    other_metrics: Sequence[Metric] = field(default_factory=tuple)

    def params_list(self) -> Sequence[EngineParams]:
        eps = self.engine_params_list
        return list(eps()) if callable(eps) else list(eps)


def resolve_evaluation(path: str, kwargs: dict | None = None) -> Evaluation:
    """Import an Evaluation by ``pkg.module:attr`` path.

    ``kwargs`` are passed when the attr is a factory callable (the way the
    reference's Evaluation objects bake in appName, user factories here take
    it as a parameter: ``pio eval pkg.mod:evaluation --params '{"app_name":
    "myapp"}'``).
    """
    from predictionio_tpu.utils.registry import resolve_import_path

    obj = resolve_import_path(path)
    if obj is None:
        raise KeyError(f"evaluation {path!r} not found")
    if callable(obj) and not isinstance(obj, Evaluation):
        obj = obj(**(kwargs or {}))
    elif kwargs:
        raise TypeError(
            f"{path!r} is an Evaluation instance; --params only applies to "
            "factory callables"
        )
    if not isinstance(obj, Evaluation):
        raise TypeError(f"{path!r} did not resolve to an Evaluation")
    return obj
