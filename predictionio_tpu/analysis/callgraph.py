"""Whole-program engine: intra-package call graph + lock acquisition graph.

The per-module rules (PIO-JAX/CONC/...) are deliberately local — they see
one function at a time.  This module is the interprocedural half: it takes
every :class:`ModuleInfo` in a scan, resolves calls *within the scanned
package* (module functions, methods via ``self``/``cls``, import aliases,
class constructors, nested defs), and derives two graphs:

  - the **call graph** — ``caller qname -> [CallSite]`` with bounded-depth
    reachability queries (PIO-JAX008 walks it from the serving seams), and
  - the **lock acquisition graph** — nodes are lock *definitions*
    (``module:Class.attr`` / ``module:VAR`` over threading.Lock/RLock/
    Condition and the ContendedLock/ContendedCondition wrappers), edges are
    "held A while acquiring B" facts, both intra-function (``with a:`` then
    ``with b:``) and through calls (holding A, call g(), g acquires B).
    Each edge carries the acquisition path so a lock-order inversion report
    can show both sides of the cycle (PIO-LOCK001).

Resolution limits (documented in docs/static_analysis.md): attribute calls
on unresolvable receivers (``self.batcher.submit()``) produce no edge;
dynamic dispatch through dicts/callbacks is invisible; ``held`` sets are
an over-approximation (an acquire() in a branch is assumed held until the
matching release() in the same function).  Everything here is stdlib-ast
only — building a Program never imports the analyzed code.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Sequence

from predictionio_tpu.analysis.rules import ModuleInfo, dotted_name

#: constructors whose result participates in the lock acquisition graph
_LOCK_CTORS = frozenset(
    (
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "predictionio_tpu.obs.contention.ContendedLock",
        "predictionio_tpu.obs.contention.ContendedCondition",
    )
)

#: ctor names whose first positional string argument is the runtime witness
#: name (what LockWitness records at acquisition time)
_WITNESS_CTORS = frozenset(
    (
        "predictionio_tpu.obs.contention.ContendedLock",
        "predictionio_tpu.obs.contention.ContendedCondition",
    )
)

#: attribute names that look like a synchronization primitive even when the
#: constructor is out of view (lock injected via a parameter); mirrors the
#: CONC003 heuristic
_LOCK_ATTR_RE = re.compile(r"^_?(lock|cond|condition|mutex|rlock)$|_lock$|_cond$")


def module_name(rel: str) -> str:
    """Dotted module name from a root-relative posix path."""
    p = rel[:-3] if rel.endswith(".py") else rel
    parts = [x for x in p.split("/") if x]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<module>"


@dataclass
class FunctionInfo:
    """One def (module function, method, or nested function)."""

    qname: str  # "pkg.mod:C.m" / "pkg.mod:f" / "pkg.mod:f.<locals>.g"
    name: str  # bare def name
    mod: ModuleInfo
    node: ast.FunctionDef | ast.AsyncFunctionDef
    cls_name: str | None  # dotted class path within the module, if a method
    parent_fn: str | None = None  # qname of the lexically enclosing function
    nested: dict[str, str] = field(default_factory=dict)  # bare -> qname


@dataclass(frozen=True)
class CallSite:
    """One resolved intra-package call."""

    callee: str  # qname
    file: str
    line: int


@dataclass
class LockNode:
    """One lock definition (or first lock-like reference)."""

    key: str  # "pkg.mod:C.attr" or "pkg.mod:VAR"
    file: str
    line: int
    witness: str | None = None  # ContendedLock/Condition runtime name


@dataclass(frozen=True)
class Acquisition:
    lock: str  # LockNode key
    file: str
    line: int
    held: tuple[str, ...]  # lock keys already held at this point


@dataclass(frozen=True)
class HeldCall:
    """A call made while holding at least one lock (resolved or not)."""

    node: ast.Call
    held: tuple[str, ...]


@dataclass
class FnSummary:
    """Per-function lock facts feeding the acquisition graph."""

    acquisitions: list[Acquisition] = field(default_factory=list)
    #: resolved calls with the held set at the call site (held may be empty)
    calls: list[tuple[str, int, tuple[str, ...]]] = field(default_factory=list)
    #: every raw Call node made while holding a lock (for PIO-LOCK002)
    held_calls: list[HeldCall] = field(default_factory=list)


@dataclass(frozen=True)
class LockEdge:
    """'held ``src`` while acquiring ``dst``', with the acquisition path."""

    src: str
    dst: str
    #: (fn qname, file, line) chain: call sites leading to dst's acquisition
    path: tuple[tuple[str, str, int], ...]


class Program:
    """All modules of one scan, indexed for interprocedural queries."""

    def __init__(self) -> None:
        self.modules: dict[str, ModuleInfo] = {}  # module name -> info
        self.module_by_rel: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.call_edges: dict[str, list[CallSite]] = {}
        self.locks: dict[str, LockNode] = {}
        self.summaries: dict[str, FnSummary] = {}
        # -- indices populated by the builder --
        self._mod_functions: dict[str, dict[str, str]] = {}
        self._methods: dict[tuple[str, str], dict[str, str]] = {}
        self._bases: dict[tuple[str, str], list[tuple[str, str]]] = {}
        #: (module, class, attr) -> (module, class) for `self.attr = C(...)`
        self._attr_types: dict[tuple[str, str, str], tuple[str, str]] = {}
        #: (module, var) -> (module, class) for module-level `V = C(...)`
        self._var_types: dict[tuple[str, str], tuple[str, str]] = {}
        self._lock_edges: list[LockEdge] | None = None

    # -- call graph queries -------------------------------------------------

    def callees(self, qname: str) -> list[CallSite]:
        return self.call_edges.get(qname, [])

    def reachable(
        self, roots: Iterable[str], max_depth: int = 4
    ) -> dict[str, tuple[tuple[str, str, int], ...]]:
        """BFS from ``roots``: reached qname -> shortest call chain.

        The chain is ``((caller, file, line), ...)`` for each hop; roots map
        to an empty chain.  Ties break on discovery order, which is the
        sorted-qname order of the roots and then call-site order, so the
        result is deterministic.
        """
        out: dict[str, tuple[tuple[str, str, int], ...]] = {}
        frontier = [(q, ()) for q in sorted(set(roots)) if q in self.functions]
        for q, _chain in frontier:
            out[q] = ()
        depth = 0
        while frontier and depth < max_depth:
            depth += 1
            nxt: list[tuple[str, tuple[tuple[str, str, int], ...]]] = []
            for q, chain in frontier:
                for site in self.call_edges.get(q, []):
                    if site.callee in out:
                        continue
                    hop = chain + ((q, site.file, site.line),)
                    out[site.callee] = hop
                    nxt.append((site.callee, hop))
            frontier = nxt
        return out

    # -- lock graph queries -------------------------------------------------

    def transitive_acquisitions(
        self, qname: str, max_depth: int = 4
    ) -> dict[str, tuple[tuple[str, str, int], ...]]:
        """Locks ``qname`` may acquire (itself or via calls, bounded depth).

        Returns lock key -> ``((fn, file, line), ...)`` chain ending at the
        acquisition site.
        """
        return self._acq(qname, max_depth, (qname,))

    def _acq(
        self, qname: str, depth: int, stack: tuple[str, ...]
    ) -> dict[str, tuple[tuple[str, str, int], ...]]:
        out: dict[str, tuple[tuple[str, str, int], ...]] = {}
        s = self.summaries.get(qname)
        if s is None:
            return out
        for a in s.acquisitions:
            out.setdefault(a.lock, ((qname, a.file, a.line),))
        if depth <= 0:
            return out
        fi = self.functions.get(qname)
        file = fi.mod.rel if fi else ""
        for callee, line, _held in s.calls:
            if callee in stack:
                continue
            for lk, chain in self._acq(
                callee, depth - 1, stack + (callee,)
            ).items():
                out.setdefault(lk, ((qname, file, line),) + chain)
        return out

    def lock_edges(self, max_depth: int = 4) -> list[LockEdge]:
        """The full acquisition-order edge set (deduped, first path wins)."""
        if self._lock_edges is not None:
            return self._lock_edges
        edges: dict[tuple[str, str], LockEdge] = {}

        def add(src: str, dst: str, path: tuple[tuple[str, str, int], ...]):
            if src != dst:
                edges.setdefault((src, dst), LockEdge(src, dst, path))

        for qname in sorted(self.summaries):
            s = self.summaries[qname]
            for a in s.acquisitions:
                for h in a.held:
                    add(h, a.lock, ((qname, a.file, a.line),))
            fi = self.functions.get(qname)
            file = fi.mod.rel if fi else ""
            for callee, line, held in s.calls:
                if not held:
                    continue
                for lk, chain in self.transitive_acquisitions(
                    callee, max_depth - 1
                ).items():
                    for h in held:
                        add(h, lk, ((qname, file, line),) + chain)
        self._lock_edges = [edges[k] for k in sorted(edges)]
        return self._lock_edges

    def witness_edge_allowlist(self, max_depth: int = 4) -> set[tuple[str, str]]:
        """Static ordered pairs in runtime-witness names.

        Maps every static edge (and its transitive closure, since a witness
        sees the whole held *stack*, not just the innermost lock) through
        the ContendedLock witness names; pairs involving locks without a
        witness name (plain threading locks — invisible at runtime) drop
        out.  The LockWitness's observed edge set must be a subset of this.
        """
        direct: dict[str, set[str]] = {}
        for e in self.lock_edges(max_depth):
            direct.setdefault(e.src, set()).add(e.dst)
        # transitive closure (the graphs here are tiny)
        closed: dict[str, set[str]] = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for src, dsts in closed.items():
                for d in list(dsts):
                    for d2 in closed.get(d, ()):
                        if d2 not in dsts:
                            dsts.add(d2)
                            changed = True
        out: set[tuple[str, str]] = set()
        for src, dsts in closed.items():
            w1 = self.locks[src].witness if src in self.locks else None
            if not w1:
                continue
            for dst in dsts:
                w2 = self.locks[dst].witness if dst in self.locks else None
                if w2 and w1 != w2:
                    out.add((w1, w2))
        return out

    # -- serialization (pio check --graph) ----------------------------------

    def to_json(self, max_depth: int = 4) -> dict:
        return {
            "version": 1,
            "callgraph": {
                "functions": sorted(self.functions),
                "edges": [
                    {
                        "caller": q,
                        "callee": s.callee,
                        "file": s.file,
                        "line": s.line,
                    }
                    for q in sorted(self.call_edges)
                    for s in self.call_edges[q]
                ],
            },
            "locks": {
                "nodes": [
                    {
                        "key": n.key,
                        "file": n.file,
                        "line": n.line,
                        "witness": n.witness,
                    }
                    for _, n in sorted(self.locks.items())
                ],
                "edges": [
                    {
                        "src": e.src,
                        "dst": e.dst,
                        "path": [
                            {"fn": fn, "file": f, "line": ln}
                            for fn, f, ln in e.path
                        ],
                    }
                    for e in self.lock_edges(max_depth)
                ],
            },
        }


# -- builder -----------------------------------------------------------------


def build_program(mods: Sequence[ModuleInfo]) -> Program:
    b = _Builder()
    for mod in mods:
        b.index_module(mod)
    b.resolve()
    return b.program


class _Builder:
    def __init__(self) -> None:
        self.program = Program()

    # -- pass 1: index defs, classes, lock definitions ----------------------

    def index_module(self, mod: ModuleInfo) -> None:
        p = self.program
        mname = module_name(mod.rel)
        p.modules[mname] = mod
        p.module_by_rel[mod.rel] = mod
        p._mod_functions.setdefault(mname, {})
        self._index_body(
            mod, mname, mod.tree.body, scope=(), cls_path=None, parent_fn=None
        )
        self._index_module_locks(mod, mname)

    def _index_body(
        self,
        mod: ModuleInfo,
        mname: str,
        body: Iterable[ast.stmt],
        scope: tuple[str, ...],
        cls_path: str | None,
        parent_fn: str | None,
    ) -> None:
        p = self.program
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                path = scope + (node.name,)
                qname = f"{mname}:{'.'.join(path)}"
                fi = FunctionInfo(
                    qname=qname,
                    name=node.name,
                    mod=mod,
                    node=node,
                    cls_name=cls_path,
                    parent_fn=parent_fn,
                )
                p.functions[qname] = fi
                if parent_fn is not None and parent_fn in p.functions:
                    p.functions[parent_fn].nested[node.name] = qname
                if not scope:
                    p._mod_functions[mname][node.name] = qname
                elif cls_path is not None and scope == tuple(
                    cls_path.split(".")
                ):
                    p._methods.setdefault((mname, cls_path), {})[
                        node.name
                    ] = qname
                # nested defs close over self: keep the class context
                self._index_body(
                    mod,
                    mname,
                    node.body,
                    path + ("<locals>",),
                    cls_path,
                    qname,
                )
            elif isinstance(node, ast.ClassDef):
                new_cls = (
                    f"{cls_path}.{node.name}" if cls_path else node.name
                )
                self._index_class_bases(mod, mname, new_cls, node)
                self._index_body(
                    mod,
                    mname,
                    node.body,
                    scope + (node.name,),
                    new_cls,
                    parent_fn,
                )
            elif isinstance(node, (ast.If, ast.Try)):
                # defs under module-level guards still exist at runtime
                for sub in ast.iter_child_nodes(node):
                    if isinstance(sub, ast.stmt):
                        self._index_body(
                            mod, mname, [sub], scope, cls_path, parent_fn
                        )

    def _index_class_bases(
        self, mod: ModuleInfo, mname: str, cls_path: str, node: ast.ClassDef
    ) -> None:
        resolved: list[tuple[str, str]] = []
        for base in node.bases:
            d = dotted_name(base)
            if d is None:
                continue
            head, dot, rest = d.partition(".")
            full = mod.aliases.get(head, head) + (dot + rest if rest else "")
            if "." not in full:
                resolved.append((mname, full))  # same-module base
            else:
                m, _, c = full.rpartition(".")
                resolved.append((m, c))
        self.program._bases[(mname, cls_path)] = resolved

    def _index_module_locks(self, mod: ModuleInfo, mname: str) -> None:
        """Module-level ``X = threading.Lock()`` style definitions, plus
        ``self.attr = <ctor>`` lock attributes anywhere in the module."""
        p = self.program
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = _resolve_in(mod, node.value.func)
            if ctor not in _LOCK_CTORS:
                continue
            witness = None
            if ctor in _WITNESS_CTORS and node.value.args:
                a0 = node.value.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    witness = a0.value
            for tgt in node.targets:
                key = None
                if isinstance(tgt, ast.Name):
                    # only module-level names define module locks
                    from predictionio_tpu.analysis.rules import (
                        enclosing_function,
                    )

                    if enclosing_function(node) is None:
                        key = f"{mname}:{tgt.id}"
                elif (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls")
                ):
                    cls = _enclosing_class_path(node)
                    if cls:
                        key = f"{mname}:{cls}.{tgt.attr}"
                if key is None:
                    continue
                prior = p.locks.get(key)
                if prior is None or prior.witness is None:
                    p.locks[key] = LockNode(
                        key=key,
                        file=mod.rel,
                        line=node.lineno,
                        witness=witness or (prior.witness if prior else None),
                    )

    # -- pass 1.5: single-assignment instance typing ------------------------

    def _index_instance_types(self) -> None:
        """``self.attr = C(...)`` and module-level ``V = C(...)`` where C is
        an intra-package class: the attribute/var is typed C, so method
        calls through it resolve.  Best-effort — conditional or re-bound
        attributes keep whatever assignment is seen last."""
        p = self.program
        for mname in sorted(p.modules):
            mod = p.modules[mname]
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                cls_key = self._class_of_ctor(mod, mname, node.value.func)
                if cls_key is None:
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")
                    ):
                        cls = _enclosing_class_path(node)
                        if cls:
                            p._attr_types[(mname, cls, tgt.attr)] = cls_key
                    elif isinstance(tgt, ast.Name):
                        from predictionio_tpu.analysis.rules import (
                            enclosing_function,
                        )

                        if enclosing_function(node) is None:
                            p._var_types[(mname, tgt.id)] = cls_key

    def _class_of_ctor(
        self, mod: ModuleInfo, mname: str, func: ast.AST
    ) -> tuple[str, str] | None:
        d = dotted_name(func)
        if d is None:
            return None
        head, dot, rest = d.partition(".")
        full = mod.aliases.get(head, head) + (dot + rest if rest else "")
        if "." not in full:
            key = (mname, full)
            return key if self._is_class(key) else None
        m, _, c = full.rpartition(".")
        key = (m, c)
        return key if self._is_class(key) else None

    def _is_class(self, key: tuple[str, str]) -> bool:
        p = self.program
        return key in p._methods or key in p._bases

    # -- pass 2: resolve calls + lock scopes per function -------------------

    def resolve(self) -> None:
        p = self.program
        self._index_instance_types()
        for qname in sorted(p.functions):
            fi = p.functions[qname]
            scanner = _FnScanner(self, fi)
            scanner.run()
            p.call_edges[qname] = scanner.sites
            p.summaries[qname] = scanner.summary

    # -- shared resolution helpers ------------------------------------------

    def resolve_dotted(self, mname: str, full: str) -> str | None:
        """qname for a canonical dotted path, trying (in order) same-module
        class methods, intra-package module functions/classes, and
        cross-module ``pkg.mod.C.m`` references."""
        p = self.program
        parts = full.split(".")
        # same-module Class.method (head is a class in mname)
        if len(parts) >= 2:
            meth = p._methods.get((mname, ".".join(parts[:-1])))
            if meth and parts[-1] in meth:
                return meth[parts[-1]]
        for i in range(len(parts) - 1, 0, -1):
            m = ".".join(parts[:i])
            if m not in p.modules:
                continue
            rest = parts[i:]
            if len(rest) == 1:
                fn = p._mod_functions.get(m, {}).get(rest[0])
                if fn:
                    return fn
                return self.method_on_class(m, rest[0], "__init__")
            if len(rest) == 2:
                hit = self.method_on_class(m, rest[0], rest[1])
                if hit:
                    return hit
            return None
        return None

    def method_on_class(
        self, mname: str, cls: str, meth: str
    ) -> str | None:
        """Method lookup through the intra-package MRO (bounded)."""
        p = self.program
        seen: set[tuple[str, str]] = set()
        queue = [(mname, cls)]
        while queue:
            key = queue.pop(0)
            if key in seen:
                continue
            seen.add(key)
            hit = p._methods.get(key, {}).get(meth)
            if hit:
                return hit
            queue.extend(p._bases.get(key, ()))
        return None

    def resolve_call_target(
        self, fi: FunctionInfo, call: ast.Call
    ) -> str | None:
        p = self.program
        mod = fi.mod
        mname = module_name(mod.rel)
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            # lexical scope chain of nested defs
            cur: FunctionInfo | None = fi
            while cur is not None:
                if name in cur.nested:
                    return cur.nested[name]
                cur = (
                    p.functions.get(cur.parent_fn)
                    if cur.parent_fn
                    else None
                )
            hit = p._mod_functions.get(mname, {}).get(name)
            if hit:
                return hit
            hit = self.method_on_class(mname, name, "__init__")
            if hit:
                return hit
            target = mod.aliases.get(name)
            if target:
                return self.resolve_dotted(mname, target)
            return None
        if isinstance(func, ast.Attribute):
            recv = func.value
            if (
                isinstance(recv, ast.Name)
                and recv.id in ("self", "cls")
                and fi.cls_name
            ):
                return self.method_on_class(mname, fi.cls_name, func.attr)
            # typed instance attribute: self.batcher.submit() where
            # __init__ did `self.batcher = MicroBatcher(...)`
            if (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id in ("self", "cls")
                and fi.cls_name
            ):
                t = self._attr_type(mname, fi.cls_name, recv.attr)
                if t is not None:
                    return self.method_on_class(t[0], t[1], func.attr)
            d = dotted_name(func)
            if d is not None:
                head, dot, rest = d.partition(".")
                full = mod.aliases.get(head, head) + (
                    dot + rest if rest else ""
                )
                hit = self.resolve_dotted(mname, full)
                if hit:
                    return hit
                # typed module-level instance: REGISTRY.counter(...)
                if "." in full:
                    owner, _, meth = full.rpartition(".")
                    om, _, ovar = owner.rpartition(".")
                    t = self.program._var_types.get(
                        (om or mname, ovar)
                    ) or self.program._var_types.get((mname, owner))
                    if t is not None:
                        return self.method_on_class(t[0], t[1], meth)
        return None

    def _attr_type(
        self, mname: str, cls: str, attr: str
    ) -> tuple[str, str] | None:
        hit = self.program._attr_types.get((mname, cls, attr))
        if hit is not None:
            return hit
        for bm, bc in self._mro(mname, cls):
            hit = self.program._attr_types.get((bm, bc, attr))
            if hit is not None:
                return hit
        return None

    def lock_key(self, fi: FunctionInfo, expr: ast.AST) -> str | None:
        """Lock-graph node key for an acquired expression, or None."""
        p = self.program
        mod = fi.mod
        mname = module_name(mod.rel)
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
            and fi.cls_name
        ):
            key = f"{mname}:{fi.cls_name}.{expr.attr}"
            if key in p.locks or _LOCK_ATTR_RE.search(expr.attr):
                if key not in p.locks:
                    p.locks[key] = LockNode(
                        key=key, file=mod.rel, line=expr.lineno
                    )
                return key
            # inherited lock attribute: match a base class definition
            for bm, bc in self._mro(mname, fi.cls_name):
                bkey = f"{bm}:{bc}.{expr.attr}"
                if bkey in p.locks:
                    return bkey
            return None
        d = dotted_name(expr)
        if d is None:
            return None
        head, dot, rest = d.partition(".")
        full = mod.aliases.get(head, head) + (dot + rest if rest else "")
        if "." not in full:
            key = f"{mname}:{full}"
            return key if key in p.locks else None
        m, _, var = full.rpartition(".")
        key = f"{m}:{var}"
        return key if key in p.locks else None

    def _mro(self, mname: str, cls: str) -> Iterator[tuple[str, str]]:
        seen: set[tuple[str, str]] = set()
        queue = list(self.program._bases.get((mname, cls), ()))
        while queue:
            key = queue.pop(0)
            if key in seen:
                continue
            seen.add(key)
            yield key
            queue.extend(self.program._bases.get(key, ()))


class _FnScanner:
    """Statement-ordered walk of one function body: resolved call sites,
    lock acquisitions with the held set, and calls made under a lock."""

    def __init__(self, builder: _Builder, fi: FunctionInfo) -> None:
        self.b = builder
        self.fi = fi
        self.sites: list[CallSite] = []
        self.summary = FnSummary()
        self.held: list[str] = []

    def run(self) -> None:
        for stmt in self.fi.node.body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return  # separate scope; scanned as its own FunctionInfo
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered: list[str] = []
            for item in stmt.items:
                self._expr(item.context_expr, skip_lock_call=True)
                if item.optional_vars is not None:
                    self._expr(item.optional_vars)
                key = self._lock_of(item.context_expr)
                if key is not None:
                    self._record_acquire(key, item.context_expr)
                    if key not in self.held:
                        self.held.append(key)
                        entered.append(key)
            for sub in stmt.body:
                self._stmt(sub)
            for key in entered:
                self.held.remove(key)
            return
        for fname, value in ast.iter_fields(stmt):
            if isinstance(value, ast.stmt):
                self._stmt(value)
            elif isinstance(value, ast.expr):
                self._expr(value)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v)
                    elif isinstance(v, ast.expr):
                        self._expr(v)
                    elif isinstance(v, (ast.withitem, ast.excepthandler)):
                        self._generic(v)
                    elif isinstance(v, getattr(ast, "match_case", ())):
                        self._generic(v)

    def _generic(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)
            else:
                self._generic(child)

    def _expr(self, expr: ast.expr, skip_lock_call: bool = False) -> None:
        """Find Call nodes inside an expression, in source order, without
        descending into lambda bodies (deferred code)."""
        for node in _walk_expr(expr):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr == "acquire":
                    key = self._lock_of(func.value)
                    if key is not None:
                        if not skip_lock_call:
                            self._record_acquire(key, node)
                            if key not in self.held:
                                self.held.append(key)
                        continue
                elif func.attr == "release":
                    key = self._lock_of(func.value)
                    if key is not None:
                        if key in self.held:
                            self.held.remove(key)
                        continue
            callee = self.b.resolve_call_target(self.fi, node)
            if callee is not None:
                self.sites.append(
                    CallSite(callee, self.fi.mod.rel, node.lineno)
                )
                self.summary.calls.append(
                    (callee, node.lineno, tuple(self.held))
                )
            if self.held:
                self.summary.held_calls.append(
                    HeldCall(node, tuple(self.held))
                )

    def _lock_of(self, expr: ast.AST) -> str | None:
        return self.b.lock_key(self.fi, expr)

    def _record_acquire(self, key: str, node: ast.AST) -> None:
        self.summary.acquisitions.append(
            Acquisition(
                lock=key,
                file=self.fi.mod.rel,
                line=getattr(node, "lineno", 1),
                held=tuple(self.held),
            )
        )


def _walk_expr(expr: ast.expr) -> Iterator[ast.AST]:
    stack: list[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


# -- module-local helpers -----------------------------------------------------


def _resolve_in(mod: ModuleInfo, expr: ast.AST) -> str:
    from predictionio_tpu.analysis.rules import resolve_name

    return resolve_name(mod, expr)


def _enclosing_class_path(node: ast.AST) -> str | None:
    from predictionio_tpu.analysis.rules import ancestors

    parts: list[str] = []
    for a in ancestors(node):
        if isinstance(a, ast.ClassDef):
            parts.append(a.name)
    if not parts:
        return None
    return ".".join(reversed(parts))
