"""Resilience lints (rule family PIO-RES*).

Motivating cases come from the failure modes the resilience layer
(predictionio_tpu/resilience/) exists to kill: an HTTP call with no
timeout turns one dead dependency into a permanently wedged thread, and a
silent ``except Exception: pass`` on a serving path swallows
``RemoteStorageError`` so a storage outage looks like healthy traffic —
degradation must be *marked* (``resilience.degrade.mark_degraded``), never
silent.
"""

from __future__ import annotations

import ast
from typing import Iterable

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    Rule,
    enclosing_function,
    resolve_call,
    resolve_name,
    rule,
)
from predictionio_tpu.analysis.rules_jax import _is_hot_function

#: calls that open a network round trip, mapped to the 0-based POSITIONAL
#: index of their ``timeout`` parameter (so a positional timeout is
#: recognized, not just the keyword spelling)
_TIMEOUT_CALLS = {
    "urllib.request.urlopen": 2,  # urlopen(url, data, timeout)
    "http.client.HTTPConnection": 2,  # (host, port, timeout)
    "http.client.HTTPSConnection": 2,
    "socket.create_connection": 1,  # (address, timeout)
}


@rule
class NetworkCallWithoutTimeout(Rule):
    """PIO-RES001: blocking network call without an explicit timeout."""

    id = "PIO-RES001"
    severity = Severity.MEDIUM
    summary = (
        "network call without an explicit timeout=; a dead peer wedges the "
        "calling thread forever"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = resolve_call(mod, node)
            if callee not in _TIMEOUT_CALLS:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs may carry it; don't guess
            if len(node.args) > _TIMEOUT_CALLS[callee]:
                continue  # timeout passed positionally
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue  # *args may carry it; don't guess
            yield self.finding(
                mod,
                node,
                f"{callee}(...) has no explicit timeout=: the default is "
                "block-forever, so one unreachable peer pins this thread "
                "until process restart; pass timeout= (capped by the "
                "request deadline where one is bound)",
            )


def _is_broad_handler(mod: ModuleInfo, handler: ast.ExceptHandler) -> bool:
    """bare ``except:`` or ``except Exception/BaseException``."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if resolve_name(mod, n) in ("Exception", "BaseException"):
            return True
    return False


def _is_silent_body(body: list[ast.stmt]) -> bool:
    """True when the handler does literally nothing (pass / ...)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        ):
            continue
        return False
    return True


@rule
class SilentExceptionSwallowOnHotPath(Rule):
    """PIO-RES002: ``except Exception: pass`` inside a serving hot-path
    function."""

    id = "PIO-RES002"
    severity = Severity.HIGH
    summary = (
        "broad except with an empty body on a serving hot path; storage "
        "outages (RemoteStorageError) vanish silently — mark degraded "
        "instead"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(mod, node):
                continue
            if not _is_silent_body(node.body):
                continue
            fn = enclosing_function(node)
            if fn is None or not _is_hot_function(fn):
                continue
            yield self.finding(
                mod,
                node,
                f"broad except with an empty body inside hot-path function "
                f"{fn.name!r}: a RemoteStorageError here makes a storage "
                "outage indistinguishable from health; at minimum call "
                "resilience.degrade.mark_degraded(...) (and log) so the "
                "fallback is visible in metrics and responses",
            )
