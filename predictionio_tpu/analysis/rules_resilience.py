"""Resilience lints (rule family PIO-RES*).

Motivating cases come from the failure modes the resilience layer
(predictionio_tpu/resilience/) exists to kill: an HTTP call with no
timeout turns one dead dependency into a permanently wedged thread, and a
silent ``except Exception: pass`` on a serving path swallows
``RemoteStorageError`` so a storage outage looks like healthy traffic —
degradation must be *marked* (``resilience.degrade.mark_degraded``), never
silent.
"""

from __future__ import annotations

import ast
from typing import Iterable

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    Rule,
    enclosing_function,
    resolve_call,
    resolve_name,
    rule,
)
from predictionio_tpu.analysis.rules_jax import _is_hot_function

#: calls that open a network round trip, mapped to the 0-based POSITIONAL
#: index of their ``timeout`` parameter (so a positional timeout is
#: recognized, not just the keyword spelling)
_TIMEOUT_CALLS = {
    "urllib.request.urlopen": 2,  # urlopen(url, data, timeout)
    "http.client.HTTPConnection": 2,  # (host, port, timeout)
    "http.client.HTTPSConnection": 2,
    "socket.create_connection": 1,  # (address, timeout)
}


@rule
class NetworkCallWithoutTimeout(Rule):
    """PIO-RES001: blocking network call without an explicit timeout."""

    id = "PIO-RES001"
    severity = Severity.MEDIUM
    summary = (
        "network call without an explicit timeout=; a dead peer wedges the "
        "calling thread forever"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = resolve_call(mod, node)
            if callee not in _TIMEOUT_CALLS:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs may carry it; don't guess
            if len(node.args) > _TIMEOUT_CALLS[callee]:
                continue  # timeout passed positionally
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue  # *args may carry it; don't guess
            yield self.finding(
                mod,
                node,
                f"{callee}(...) has no explicit timeout=: the default is "
                "block-forever, so one unreachable peer pins this thread "
                "until process restart; pass timeout= (capped by the "
                "request deadline where one is bound)",
            )


def _is_broad_handler(mod: ModuleInfo, handler: ast.ExceptHandler) -> bool:
    """bare ``except:`` or ``except Exception/BaseException``."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if resolve_name(mod, n) in ("Exception", "BaseException"):
            return True
    return False


def _is_silent_body(body: list[ast.stmt]) -> bool:
    """True when the handler does literally nothing (pass / ...)."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        ):
            continue
        return False
    return True


#: attribute spellings that publish bytes/text to a path
_WRITE_ATTRS = ("write_bytes", "write_text")

#: resolved call names that atomically commit a tmp write (attribute
#: spellings like ``tmp.replace(final)`` are arity-checked in
#: ``_is_commit_call`` so ``str.replace(old, new)`` never qualifies)
_COMMIT_CALLS = ("os.replace", "os.rename", "os.renames", "shutil.move")


def _write_mode(call: ast.Call, mode_pos: int) -> bool:
    """True when an ``open(...)``/``.open(...)`` call's mode argument
    spells write/append.  ``mode_pos`` is the positional index of the
    mode: 1 for builtin ``open(path, mode)``, 0 for the ``Path.open(mode)``
    method spelling."""
    mode = None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None and len(call.args) > mode_pos:
        mode = call.args[mode_pos]
    if not isinstance(mode, ast.Constant) or not isinstance(mode.value, str):
        return False
    return any(c in mode.value for c in "wax+")


def _is_commit_call(node: ast.Call, mod: ModuleInfo) -> bool:
    """An atomic-rename commit step.  Attribute spellings are arity-
    checked so ``str.replace(old, new)`` (two args) never passes for
    ``Path.replace(target)`` (one arg); ``os.replace``/``shutil.move``
    resolve by name regardless of arity."""
    if resolve_call(mod, node) in _COMMIT_CALLS:
        return True
    if not isinstance(node.func, ast.Attribute):
        return False
    attr = node.func.attr
    if attr in ("replace", "rename") and len(node.args) == 1:
        return True  # pathlib: tmp.replace(final) / tmp.rename(final)
    if attr in ("mv", "move", "renames"):
        return True  # fsspec/shutil-style two-arg movers; str has neither
    return False


def _function_commits(fn: ast.AST, mod: ModuleInfo) -> bool:
    """Does this function ever rename/replace something into place?"""
    return any(
        isinstance(node, ast.Call) and _is_commit_call(node, mod)
        for node in ast.walk(fn)
    )


@rule
class DirectWriteToPersistencePath(Rule):
    """PIO-RES003: storage-module write without a tmp-write + rename
    commit step."""

    id = "PIO-RES003"
    severity = Severity.MEDIUM
    summary = (
        "direct write to a final persistence path; a crash mid-write "
        "leaves a torn blob readers will load — write a tmp file and "
        "rename/replace it into place"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        # persistence modules only: the data/storage backends and fixtures
        # shaped like them — the tmp-write + atomic-rename contract is what
        # makes lifecycle generation flips crash-safe
        if "storage" not in mod.rel.replace("\\", "/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            is_write = (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _WRITE_ATTRS
            )
            if not is_write:
                if resolve_call(mod, node) == "open":
                    is_write = _write_mode(node, mode_pos=1)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "open"
                ):
                    # method spelling: Path.open("w") / fs.open(path, "wb")
                    # — the mode may sit at either position
                    is_write = _write_mode(node, mode_pos=0) or _write_mode(
                        node, mode_pos=1
                    )
            if not is_write:
                continue
            fn = enclosing_function(node)
            if fn is not None and _function_commits(fn, mod):
                continue  # tmp-write + rename/replace: the durable pattern
            if fn is None and _function_commits(mod.tree, mod):
                continue  # module-level write with a module-level commit
            target = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else "open"
            )
            yield self.finding(
                mod,
                node,
                f"{target}(...) writes the final persistence path directly: "
                "a crash between the first byte and the last leaves a torn "
                "blob that later reads will trust; write to a uniquely-"
                "named tmp file, fsync it, then os.replace() it into place "
                "(see data/storage/localfs_models.py)",
            )


#: keyword names that bound a parquet read (either prunes what is
#: materialized): projection or predicate
_READ_BOUND_KWARGS = frozenset({"columns", "filters", "filter"})


@rule
class FullTableMaterializationInStoragePath(Rule):
    """PIO-RES004: unbounded parquet read in a storage-pathed module."""

    id = "PIO-RES004"
    severity = Severity.MEDIUM
    summary = (
        "full-table parquet materialization: read_table/to_table/"
        "ParquetFile(...).read() without columns= or filters= decodes "
        "every row group and column of the file"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        # storage modules only: the event tier at 100M+ rows lives or
        # dies on predicate/column pushdown (docs/data_plane.md); an
        # unbounded read_table on a scan path silently drags the whole
        # log through memory
        if "storage" not in mod.rel.replace("\\", "/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if any(kw.arg is None for kw in node.keywords):
                continue  # **kwargs may carry a bound; don't guess
            kwargs = {kw.arg for kw in node.keywords}
            if kwargs & _READ_BOUND_KWARGS:
                continue
            callee = resolve_call(mod, node)
            what = None
            if callee == "pyarrow.parquet.read_table":
                what = "pyarrow.parquet.read_table(...)"
            elif isinstance(node.func, ast.Attribute):
                if node.func.attr == "to_table":
                    what = ".to_table(...)"
                elif (
                    node.func.attr == "read"
                    and isinstance(node.func.value, ast.Call)
                    and resolve_call(mod, node.func.value)
                    == "pyarrow.parquet.ParquetFile"
                ):
                    what = "pyarrow.parquet.ParquetFile(...).read()"
            if what is None:
                continue
            yield self.finding(
                mod,
                node,
                f"{what} without columns= or filters= materializes the "
                "whole file; scans at event-store scale must push the "
                "projection/predicate into the reader (pass columns= "
                "and/or filters=/filter=, even if spelled out in full, "
                "so the read is a deliberate bound)",
            )


@rule
class SilentExceptionSwallowOnHotPath(Rule):
    """PIO-RES002: ``except Exception: pass`` inside a serving hot-path
    function."""

    id = "PIO-RES002"
    severity = Severity.HIGH
    summary = (
        "broad except with an empty body on a serving hot path; storage "
        "outages (RemoteStorageError) vanish silently — mark degraded "
        "instead"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(mod, node):
                continue
            if not _is_silent_body(node.body):
                continue
            fn = enclosing_function(node)
            if fn is None or not _is_hot_function(fn):
                continue
            yield self.finding(
                mod,
                node,
                f"broad except with an empty body inside hot-path function "
                f"{fn.name!r}: a RemoteStorageError here makes a storage "
                "outage indistinguishable from health; at minimum call "
                "resilience.degrade.mark_degraded(...) (and log) so the "
                "fallback is visible in metrics and responses",
            )
