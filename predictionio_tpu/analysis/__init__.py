"""`pio check`: JAX-aware static analysis for DASE engines and serving code.

The JVM reference leans on scalac to reject a mis-wired engine before
`pio train` runs; this package is the Python port's guardrail.  Three rule
families:

  - PIO-JAX00x  — hot-path device syncs, import-time device work, traced
                  Python branches in @jit, recompile hazards (rules_jax)
  - PIO-CONC00x — blocking calls in async handlers, busy-wait polls,
                  unlocked mutation of lock-guarded state (rules_concurrency)
  - PIO-LOCK00x — whole-program lock-order inversions and blocking calls
                  held under a lock, over the call/lock graph built by
                  callgraph.py (rules_locks); PIO-JAX008 rides the same
                  graph for transitive hot-path syncs
  - PIO-RES00x  — network calls without timeouts, silent exception
                  swallowing on serving hot paths (rules_resilience)
  - PIO-OBS00x  — route dispatch that bypasses the request-latency
                  middleware, creating metrics-dark traffic (rules_obs)
  - PIO-DASE00x — DataSource->Preparator->Algorithm->Serving signature /
                  params-dataclass contract checks (contract; import-based,
                  lazily loaded so plain lint runs never import jax)

Suppression is inline (``# pio: ignore[RULE]``) or via a checked-in
baseline with per-entry justifications; `pio check` exits 0 clean /
1 findings / 2 usage-or-parse error.
"""

from predictionio_tpu.analysis.analyzer import (  # noqa: F401
    AnalysisReport,
    analyze_paths,
    analyze_source,
    filter_severity,
    render_json,
    render_sarif,
    render_text,
)
from predictionio_tpu.analysis.baseline import (  # noqa: F401
    DEFAULT_BASELINE_NAME,
    Baseline,
    BaselineError,
)
from predictionio_tpu.analysis.callgraph import (  # noqa: F401
    Program,
    build_program,
)
from predictionio_tpu.analysis.findings import Finding, Severity  # noqa: F401
from predictionio_tpu.analysis.rules import (  # noqa: F401
    ALL_RULES,
    ProgramRule,
    Rule,
)

# importing the rule modules registers them in ALL_RULES
from predictionio_tpu.analysis import rules_concurrency  # noqa: E402,F401
from predictionio_tpu.analysis import rules_jax  # noqa: E402,F401
from predictionio_tpu.analysis import rules_locks  # noqa: E402,F401
from predictionio_tpu.analysis import rules_obs  # noqa: E402,F401
from predictionio_tpu.analysis import rules_resilience  # noqa: E402,F401

__all__ = [
    "ALL_RULES",
    "AnalysisReport",
    "Baseline",
    "BaselineError",
    "DEFAULT_BASELINE_NAME",
    "Finding",
    "Program",
    "ProgramRule",
    "Rule",
    "Severity",
    "analyze_paths",
    "analyze_source",
    "build_program",
    "filter_severity",
    "render_json",
    "render_sarif",
    "render_text",
]
