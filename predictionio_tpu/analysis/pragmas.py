"""Inline suppression pragmas: ``# pio: ignore[RULE]``.

A pragma on the flagged line suppresses matching findings on that line; a
pragma on a comment-only line suppresses findings on the next line (for
sites where the flagged statement has no room for a trailing comment).
``# pio: ignore[*]`` suppresses every rule on the line.
"""

from __future__ import annotations

import re

from predictionio_tpu.analysis.findings import Finding

PRAGMA_RE = re.compile(r"#\s*pio:\s*ignore\[([A-Za-z0-9_*,\-\s]*)\]")


def pragma_map(lines: list[str]) -> dict[int, set[str]]:
    """1-based line number -> set of suppressed rule ids ('*' = all)."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        m = PRAGMA_RE.search(text)
        if not m:
            continue
        ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
        if not ids:
            continue
        out.setdefault(i, set()).update(ids)
        if text.lstrip().startswith("#"):  # comment-only line: covers next
            out.setdefault(i + 1, set()).update(ids)
    return out


def is_suppressed(finding: Finding, pragmas: dict[int, set[str]]) -> bool:
    ids = pragmas.get(finding.line)
    return bool(ids) and ("*" in ids or finding.rule in ids)
