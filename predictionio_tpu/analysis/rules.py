"""Rule framework: per-module AST context, rule registry, shared helpers.

Rules operate on a :class:`ModuleInfo` — a parsed module with parent links
annotated on every node and an import-alias table so ``jnp.dot`` and
``jax.numpy.dot`` resolve to the same canonical name.  Registration is a
decorator (:func:`rule`); the analyzer runs every registered rule unless a
subset is requested.
"""

from __future__ import annotations

import abc
import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

from predictionio_tpu.analysis.findings import Finding, Severity

#: attribute set on every AST node pointing at its syntactic parent
_PARENT = "_pio_parent"


@dataclass
class ModuleInfo:
    """One parsed module handed to every rule."""

    path: Path  # absolute filesystem path
    rel: str  # posix path relative to the analysis root (finding.file)
    source: str
    lines: list[str] = field(default_factory=list)
    tree: ast.Module | None = None
    aliases: dict[str, str] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def parse_module(path: Path, rel: str, source: str) -> ModuleInfo:
    tree = ast.parse(source, filename=str(path))
    annotate_parents(tree)
    return ModuleInfo(
        path=path,
        rel=rel,
        source=source,
        lines=source.splitlines(),
        tree=tree,
        aliases=build_aliases(tree),
    )


def annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, _PARENT, node)


def parent(node: ast.AST) -> ast.AST | None:
    return getattr(node, _PARENT, None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing_function(
    node: ast.AST,
) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return a
    return None


def build_aliases(tree: ast.Module) -> dict[str, str]:
    """Local name -> canonical dotted path, from module-level imports
    (including those under module-level if/try, but NOT function-local
    imports — a `from time import sleep` inside one function must not make
    a bare `sleep` in another function resolve to time.sleep).

    ``import numpy as np`` -> {'np': 'numpy'};
    ``from jax import jit`` -> {'jit': 'jax.jit'};
    ``import jax.numpy as jnp`` -> {'jnp': 'jax.numpy'}.
    """
    aliases: dict[str, str] = {}
    for node in walk_skipping_defs(tree.body):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.partition(".")[0]] = (
                    a.name if a.asname else a.name.partition(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(expr: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains; None for anything else."""
    parts: list[str] = []
    cur = expr
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def resolve_name(mod: ModuleInfo, expr: ast.AST) -> str:
    """Canonical dotted name of an expression through the alias table.

    Attribute access on a non-name receiver (``x.item``) renders as
    ``*.item`` so rules can match method names independent of the receiver.
    """
    d = dotted_name(expr)
    if d is None:
        if isinstance(expr, ast.Attribute):
            return "*." + expr.attr
        return ""
    head, dot, rest = d.partition(".")
    base = mod.aliases.get(head, head)
    return base + dot + rest if rest else base


def resolve_call(mod: ModuleInfo, node: ast.Call) -> str:
    return resolve_name(mod, node.func)


def walk_skipping_defs(body: Iterable[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class defs
    or lambda bodies — code in those scopes is deferred, not inline."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


# -- jit decorator introspection (shared by the JAX rules) -------------------

_JIT_NAMES = frozenset(("jax.jit", "jax.pjit", "jax.pmap"))


def _is_jit_expr(mod: ModuleInfo, expr: ast.AST) -> bool:
    return resolve_name(mod, expr) in _JIT_NAMES


def jit_decorator_info(
    mod: ModuleInfo, fn: ast.FunctionDef | ast.AsyncFunctionDef
) -> tuple[bool, set[str], set[int]]:
    """(is_jitted, static_argnames, static_argnums) from the decorator list.

    Recognizes ``@jax.jit``, ``@jit`` (aliased import), and
    ``@partial(jax.jit, static_argnames=..., static_argnums=...)``.
    """
    static_names: set[str] = set()
    static_nums: set[int] = set()
    jitted = False
    for dec in fn.decorator_list:
        kwargs: list[ast.keyword] = []
        if _is_jit_expr(mod, dec):
            jitted = True
        elif isinstance(dec, ast.Call):
            callee = resolve_name(mod, dec.func)
            if callee in _JIT_NAMES:
                jitted = True
                kwargs = dec.keywords
            elif callee == "functools.partial" and dec.args and _is_jit_expr(
                mod, dec.args[0]
            ):
                jitted = True
                kwargs = dec.keywords
        for kw in kwargs:
            if kw.arg == "static_argnames":
                static_names |= _const_strings(kw.value)
            elif kw.arg == "static_argnums":
                static_nums |= _const_ints(kw.value)
    return jitted, static_names, static_nums


def _const_strings(expr: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


def _const_ints(expr: ast.AST) -> set[int]:
    out: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            out.add(node.value)
    return out


# -- registry ---------------------------------------------------------------


class Rule(abc.ABC):
    """One lint: an id, a fixed severity, and an AST check."""

    id: str = ""
    severity: Severity = Severity.MEDIUM
    summary: str = ""

    @abc.abstractmethod
    def check(self, mod: ModuleInfo) -> Iterable[Finding]: ...

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.id,
            severity=self.severity,
            file=mod.rel,
            line=line,
            col=getattr(node, "col_offset", 0) + 1,
            message=message,
            source=mod.line_text(line),
        )


class ProgramRule(Rule):
    """Whole-program rule: runs once over every parsed module at a time.

    Per-module ``check`` is a no-op; the analyzer calls ``check_program``
    with a :class:`predictionio_tpu.analysis.callgraph.Program` built from
    all modules in the scan.  Findings still carry a per-file ``rel`` path,
    so pragma and baseline suppression work unchanged.  Note the scan scope
    IS the analysis scope: running a program rule on a single file cannot
    see edges into other modules.
    """

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    @abc.abstractmethod
    def check_program(self, program) -> Iterable[Finding]: ...


#: id -> rule instance; populated by the @rule decorator at import time
ALL_RULES: dict[str, Rule] = {}


def rule(cls: type[Rule]) -> type[Rule]:
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in ALL_RULES:
        raise ValueError(f"duplicate rule id {inst.id}")
    ALL_RULES[inst.id] = inst
    return cls
