"""DASE contract checks: the Scala compiler's job, done at pre-flight.

The reference's Engine[TD, EI, PD, Q, P, A] is type-checked by scalac
before `pio train` can run (controller/Engine.scala:82); the Python port
wires DataSource -> Preparator -> Algorithm -> Serving by name, so a wrong
arity or a params typo only explodes mid-training.  These checks load an
engine factory and statically verify every registered component *before
any device work starts*:

  - each stage class implements its required methods with a compatible
    positional arity (``read_training(self, ctx)``, ``prepare(self, ctx,
    td)``, ``train``/``predict``, ``serve``/``supplement``);
  - no stage class is still abstract;
  - a class registered for one stage isn't actually a different stage's
    base (Algorithm wired into the serving slot, etc.);
  - ``params_class`` is a dataclass, its ``params_aliases`` point at real
    fields, and the component constructor accepts a params argument.

Used standalone via ``pio check --engine NAME`` and as the `pio train` /
`pio deploy` pre-flight (skippable with ``--no-check``).  Unlike the AST
rules this module imports the engine code, so it lives behind lazy imports.
"""

from __future__ import annotations

import dataclasses
import inspect
from pathlib import Path
from typing import Any, Iterator

from predictionio_tpu.analysis.findings import Finding, Severity

#: stage name -> [(method, n_positional_args_including_self, required)]
_STAGE_METHODS: dict[str, list[tuple[str, int, bool]]] = {
    "datasource": [("read_training", 2, True), ("read_eval", 2, False)],
    "preparator": [("prepare", 3, True)],
    "algorithm": [
        ("train", 3, True),
        ("predict", 3, True),
        ("batch_predict", 3, False),
    ],
    "serving": [("serve", 3, True), ("supplement", 2, False)],
}


def _finding(
    rule: str, cls_or_obj: Any, message: str, root: Path | None
) -> Finding:
    file, line = _locate(cls_or_obj)
    if root is not None and file:
        try:
            file = Path(file).resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            pass
    source = ""
    if file and line:
        try:
            source = (
                Path(file if Path(file).is_absolute() else root / file)
                .read_text()
                .splitlines()[line - 1]
                .strip()
            )
        except (OSError, IndexError, TypeError):
            source = ""
    return Finding(
        rule=rule,
        severity=Severity.HIGH,
        file=file or "<engine>",
        line=line or 1,
        col=1,
        message=message,
        source=source,
    )


def _locate(obj: Any) -> tuple[str, int]:
    try:
        file = inspect.getsourcefile(obj) or ""
        _, line = inspect.getsourcelines(obj)
        return file, line
    except (OSError, TypeError):
        return "", 0


def _positional_arity_error(fn: Any, n: int) -> str | None:
    """None if ``fn(*n args)`` can bind, else a description of the mismatch."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None  # uninspectable (C-level): give it the benefit of doubt
    min_pos = max_pos = 0
    has_var = False
    for p in sig.parameters.values():
        if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
        ):
            max_pos += 1
            if p.default is inspect.Parameter.empty:
                min_pos += 1
        elif p.kind == inspect.Parameter.VAR_POSITIONAL:
            has_var = True
        elif (
            p.kind == inspect.Parameter.KEYWORD_ONLY
            and p.default is inspect.Parameter.empty
        ):
            return f"has a required keyword-only parameter {p.name!r}"
    if min_pos > n:
        return (
            f"requires {min_pos} positional argument(s) but the framework "
            f"calls it with {n}"
        )
    if not has_var and max_pos < n:
        return (
            f"accepts at most {max_pos} positional argument(s) but the "
            f"framework calls it with {n}"
        )
    return None


def _stage_bases() -> dict[str, type]:
    from predictionio_tpu.core.base import (
        Algorithm,
        DataSource,
        Preparator,
        Serving,
    )

    return {
        "datasource": DataSource,
        "preparator": Preparator,
        "algorithm": Algorithm,
        "serving": Serving,
    }


def check_component(
    stage: str, name: str, cls: type, root: Path | None = None
) -> Iterator[Finding]:
    """Contract findings for one registered component class."""
    label = f"{stage} component {name or cls.__name__!r}"
    bases = _stage_bases()

    # wired into the wrong slot? (an Algorithm registered as serving, etc.)
    for other_stage, base in bases.items():
        if other_stage == stage:
            continue
        if isinstance(cls, type) and issubclass(cls, base):
            yield _finding(
                "PIO-DASE001",
                cls,
                f"{label}: {cls.__name__} subclasses the "
                f"{base.__name__} base — it is wired into the wrong "
                f"DASE slot",
                root,
            )
            return

    abstract = getattr(cls, "__abstractmethods__", frozenset())
    if abstract:
        yield _finding(
            "PIO-DASE001",
            cls,
            f"{label}: {cls.__name__} is still abstract "
            f"(unimplemented: {sorted(abstract)})",
            root,
        )
    for method, n, required in _STAGE_METHODS[stage]:
        fn = getattr(cls, method, None)
        if fn is None or not callable(fn):
            if required:
                yield _finding(
                    "PIO-DASE001",
                    cls,
                    f"{label}: missing required method {method!r}",
                    root,
                )
            continue
        # only check methods the class (or a non-framework base) defines;
        # inherited framework defaults are correct by construction
        err = _positional_arity_error(fn, n)
        if err is not None:
            yield _finding(
                "PIO-DASE002",
                fn,
                f"{label}: {method}() {err} "
                f"(expected {_expected_sig(stage, method)})",
                root,
            )

    yield from _check_params(stage, name, cls, root)


def _expected_sig(stage: str, method: str) -> str:
    sigs = {
        ("datasource", "read_training"): "read_training(self, ctx)",
        ("datasource", "read_eval"): "read_eval(self, ctx)",
        ("preparator", "prepare"): "prepare(self, ctx, td)",
        ("algorithm", "train"): "train(self, ctx, pd)",
        ("algorithm", "predict"): "predict(self, model, query)",
        ("algorithm", "batch_predict"): "batch_predict(self, model, queries)",
        ("serving", "serve"): "serve(self, query, predictions)",
        ("serving", "supplement"): "supplement(self, query)",
    }
    return sigs.get((stage, method), method)


def _check_params(
    stage: str, name: str, cls: type, root: Path | None
) -> Iterator[Finding]:
    label = f"{stage} component {name or cls.__name__!r}"
    params_cls = getattr(cls, "params_class", None)
    if params_cls is None:
        return
    if not dataclasses.is_dataclass(params_cls):
        yield _finding(
            "PIO-DASE003",
            cls,
            f"{label}: params_class {params_cls!r} is not a dataclass — "
            "extract_params cannot build it from engine.json",
            root,
        )
        return
    fields = {f.name for f in dataclasses.fields(params_cls)}
    aliases = dict(getattr(params_cls, "params_aliases", {}) or {})
    for json_name, field_name in aliases.items():
        if field_name not in fields:
            yield _finding(
                "PIO-DASE003",
                params_cls,
                f"{label}: params_aliases maps {json_name!r} to "
                f"{field_name!r}, which is not a field of "
                f"{params_cls.__name__} (fields: {sorted(fields)})",
                root,
            )
    # the doer contract: Cls(params) must be constructible
    from predictionio_tpu.utils.registry import _takes_argument

    if not _takes_argument(cls):
        yield _finding(
            "PIO-DASE003",
            cls,
            f"{label}: declares params_class "
            f"{params_cls.__name__} but its constructor takes no "
            "positional argument — the framework instantiates components "
            "as Cls(params)",
            root,
        )


def check_engine(
    engine: Any, factory_name: str = "", root: Path | None = None
) -> list[Finding]:
    """Contract findings for an instantiated Engine's class maps."""
    from predictionio_tpu.core.engine import Engine

    if not isinstance(engine, Engine):
        return [
            _finding(
                "PIO-DASE001",
                type(engine),
                f"engine factory {factory_name!r} returned "
                f"{type(engine).__name__}, not an Engine",
                root,
            )
        ]
    stage_maps = {
        "datasource": engine.datasource_classes,
        "preparator": engine.preparator_classes,
        "algorithm": engine.algorithm_classes,
        "serving": engine.serving_classes,
    }
    findings: list[Finding] = []
    for stage, classes in stage_maps.items():
        if not classes:
            findings.append(
                _finding(
                    "PIO-DASE001",
                    type(engine),
                    f"engine {factory_name!r}: no {stage} class registered",
                    root,
                )
            )
            continue
        for name, cls in classes.items():
            findings.extend(check_component(stage, name, cls, root=root))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def check_engine_contract(
    factory_name: str, root: Path | None = None
) -> list[Finding]:
    """Resolve a factory by name/import path and check its engine.

    Factory resolution or construction failures become findings (the
    pre-flight must report them, not crash).
    """
    from predictionio_tpu.core.engine import resolve_engine_factory

    try:
        factory = resolve_engine_factory(factory_name)
    except Exception as e:
        # KeyError for unknown names, but an import-path factory can raise
        # anything at module import — the pre-flight reports, never crashes
        return [
            Finding(
                rule="PIO-DASE001",
                severity=Severity.HIGH,
                file="<engine>",
                line=1,
                col=1,
                message=f"engine factory {factory_name!r} not resolvable: "
                f"{type(e).__name__}: {e}",
            )
        ]
    try:
        engine = factory()
    except Exception as e:
        return [
            _finding(
                "PIO-DASE001",
                factory,
                f"engine factory {factory_name!r} raised at construction: "
                f"{type(e).__name__}: {e}",
                root,
            )
        ]
    return check_engine(engine, factory_name, root=root)
