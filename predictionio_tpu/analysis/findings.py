"""Finding and severity model for `pio check`.

The reference framework gets its pre-flight guarantees from the JVM
compiler (Scala type-checks the DASE wiring before `pio train` ever runs);
this package is the Python port's replacement: every rule reports
:class:`Finding` records with a ``file:line`` anchor so violations surface
before an engine reaches the device, not under load.

Kept stdlib-only on purpose — the analyzer must be importable (and fast)
in CI containers that have no jax wheel at all.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any


class Severity(enum.IntEnum):
    """Ordered severity; comparisons follow the int value."""

    LOW = 10
    MEDIUM = 20
    HIGH = 30

    @classmethod
    def parse(cls, text: str) -> "Severity":
        try:
            return cls[str(text).strip().upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {text!r}; expected one of "
                f"{[s.name.lower() for s in cls]}"
            ) from None

    def __str__(self) -> str:  # render as 'high', parse back with parse()
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line.

    ``source`` carries the stripped text of the flagged line: the baseline
    matches on (rule, file, source) rather than line numbers, so unrelated
    edits above a baselined site do not invalidate the suppression.
    """

    rule: str
    severity: Severity
    file: str
    line: int
    col: int
    message: str
    source: str = ""

    def text(self) -> str:
        return (
            f"{self.file}:{self.line}:{self.col}: "
            f"{self.severity.name} {self.rule} {self.message}"
        )

    def to_json_dict(self) -> dict[str, Any]:
        return {
            "rule": self.rule,
            "severity": str(self.severity),
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "source": self.source,
        }

    @classmethod
    def from_json_dict(cls, d: dict[str, Any]) -> "Finding":
        """Inverse of :meth:`to_json_dict` (check-cache round trips)."""
        return cls(
            rule=str(d["rule"]),
            severity=Severity.parse(str(d["severity"])),
            file=str(d["file"]),
            line=int(d["line"]),
            col=int(d["col"]),
            message=str(d["message"]),
            source=str(d.get("source", "")),
        )
