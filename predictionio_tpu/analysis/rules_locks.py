"""Whole-program lock-order rules (rule family PIO-LOCK*).

Both rules run over the lock acquisition graph built by
``analysis/callgraph.py`` — nodes are lock definitions, edges are "held A
while acquiring B" facts collected intra-function and through resolved
calls (bounded depth).  The motivating hazard is this codebase's own
serving process: ~20 locks coordinate MicroBatcher waves, generation
swaps, breakers and the cost ledger, and no local rule can see an
inversion between two modules or a ``future.result()`` two calls below a
``with self._lock:``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from predictionio_tpu.analysis.callgraph import LockEdge, Program
from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    ProgramRule,
    parent,
    resolve_call,
    resolve_name,
    rule,
    walk_skipping_defs,
)
from predictionio_tpu.analysis.rules_concurrency import (
    _BLOCKING_CALLS,
    _BLOCKING_METHODS,
)

#: how deep interprocedural lock propagation follows resolved calls
LOCK_GRAPH_DEPTH = 4


def _fmt_path(path: tuple[tuple[str, str, int], ...]) -> str:
    return " -> ".join(f"{fn} ({file}:{line})" for fn, file, line in path)


def _program_finding(
    rule_obj, program: Program, file: str, line: int, message: str
) -> Finding:
    mod = program.module_by_rel.get(file)
    src = mod.line_text(line) if mod is not None else ""
    return Finding(
        rule=rule_obj.id,
        severity=rule_obj.severity,
        file=file,
        line=line,
        col=1,
        message=message,
        source=src,
    )


@rule
class LockOrderInversion(ProgramRule):
    """PIO-LOCK001: two lock-acquisition paths with opposite order."""

    id = "PIO-LOCK001"
    severity = Severity.HIGH
    summary = (
        "lock-order inversion: the same two locks are acquired in opposite "
        "orders on different paths — deadlock under concurrency"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        edges = {
            (e.src, e.dst): e for e in program.lock_edges(LOCK_GRAPH_DEPTH)
        }
        reported: set[frozenset[str]] = set()
        # pairwise inversions (A->B and B->A both observed)
        for a, b in sorted(edges):
            if a >= b or (b, a) not in edges:
                continue
            e1, e2 = edges[(a, b)], edges[(b, a)]
            reported.add(frozenset((a, b)))
            yield self._inversion_finding(program, e1, e2)
        # longer cycles (A->B->C->A with no direct back edge): one finding
        # per strongly-connected component not already covered pairwise
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            if any(
                frozenset((a, b)) in reported for a in scc for b in scc if a < b
            ):
                continue
            cycle = _find_cycle(sorted(scc), adj)
            if cycle is None:
                continue
            chain = [edges[(cycle[i], cycle[i + 1])] for i in range(len(cycle) - 1)]
            first = chain[0].path[0]
            msg = (
                "lock-order cycle through "
                + " -> ".join(f"'{k}'" for k in cycle)
                + ": "
                + "; ".join(
                    f"'{e.src}' -> '{e.dst}' via {_fmt_path(e.path)}"
                    for e in chain
                )
                + " — threads traversing different arcs of this cycle can "
                "deadlock; pick one global acquisition order"
            )
            yield _program_finding(self, program, first[1], first[2], msg)

    def _inversion_finding(
        self, program: Program, e1: LockEdge, e2: LockEdge
    ) -> Finding:
        first = e1.path[0]
        msg = (
            f"lock-order inversion between '{e1.src}' and '{e1.dst}': "
            f"'{e1.src}' is held while acquiring '{e1.dst}' via "
            f"{_fmt_path(e1.path)}, but '{e2.src}' is held while acquiring "
            f"'{e2.dst}' via {_fmt_path(e2.path)}; two threads taking these "
            "paths concurrently can deadlock — pick one global acquisition "
            "order"
        )
        return _program_finding(self, program, first[1], first[2], msg)


def _sccs(adj: dict[str, set[str]]) -> list[set[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    out: list[set[str]] = []
    counter = [0]
    nodes = sorted(set(adj) | {d for v in adj.values() for d in v})

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                comp: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    for n in nodes:
        if n not in index:
            strongconnect(n)
    return out


def _find_cycle(
    nodes: list[str], adj: dict[str, set[str]]
) -> list[str] | None:
    """A simple cycle through the smallest node of an SCC (BFS back-path)."""
    start = nodes[0]
    scc = set(nodes)
    prev: dict[str, str] = {}
    queue = [start]
    seen = {start}
    while queue:
        v = queue.pop(0)
        for w in sorted(adj.get(v, ())):
            if w not in scc:
                continue
            if w == start:
                cycle = [start]
                cur = v
                back = []
                while cur != start:
                    back.append(cur)
                    cur = prev[cur]
                cycle.extend(reversed(back))
                cycle.append(start)
                return cycle
            if w not in seen:
                seen.add(w)
                prev[w] = v
                queue.append(w)
    return None


#: receiver-name fragments that mark a ``.join()`` as a thread/process wait
#: (str.join is everywhere — the receiver must look like an executor)
_JOIN_RECV_RE = re.compile(r"thread|worker|proc|executor|pool", re.I)


def _has_timeout(node: ast.Call) -> bool:
    """True when the call passes a (non-None) timeout: first positional arg
    or ``timeout=`` keyword — ``fut.result(5)``, ``t.join(timeout=2)``."""
    if node.args and not (
        isinstance(node.args[0], ast.Constant) and node.args[0].value is None
    ):
        return True
    for kw in node.keywords:
        if kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant) and kw.value.value is None
        ):
            return True
    return False


def blocking_label(mod: ModuleInfo, node: ast.Call) -> str | None:
    """Label when ``node`` blocks the calling thread (exemptions applied):
    awaited calls yield the loop; ``.result``/``.join`` with a timeout are
    bounded waits.  Network/subprocess/sleep are flagged regardless of
    timeout — holding a lock across I/O is the hazard itself."""
    if isinstance(parent(node), ast.Await):
        return None
    callee = resolve_call(mod, node)
    if callee in _BLOCKING_CALLS:
        return callee
    if not isinstance(node.func, ast.Attribute):
        return None
    method = node.func.attr
    if method in _BLOCKING_METHODS:
        return f"*.{method}"
    if method == "result":
        recv = node.func.value
        if isinstance(recv, ast.Constant):
            return None
        return None if _has_timeout(node) else "*.result"
    if method == "join":
        recv_name = resolve_name(mod, node.func.value)
        if not _JOIN_RECV_RE.search(recv_name):
            return None
        return None if _has_timeout(node) else "*.join"
    return None


@rule
class BlockingCallUnderLock(ProgramRule):
    """PIO-LOCK002: blocking call while holding a lock (direct or through
    resolved calls within bounded depth)."""

    id = "PIO-LOCK002"
    severity = Severity.HIGH
    summary = (
        "blocking call (socket/urlopen/result/sleep/join/subprocess) while "
        "holding a lock; every other thread needing the lock stalls behind "
        "the I/O — or deadlocks if the waited work needs the same lock"
    )

    def check_program(self, program: Program) -> Iterable[Finding]:
        direct = self._direct_blocking(program)
        seen: set[tuple[str, int, str]] = set()
        # direct: the blocking call itself sits under a `with lock:`
        for qname in sorted(program.summaries):
            s = program.summaries[qname]
            fi = program.functions.get(qname)
            if fi is None:
                continue
            for hc in s.held_calls:
                label = blocking_label(fi.mod, hc.node)
                if label is None:
                    continue
                key = (fi.mod.rel, hc.node.lineno, label)
                if key in seen:
                    continue
                seen.add(key)
                yield _program_finding(
                    self,
                    program,
                    fi.mod.rel,
                    hc.node.lineno,
                    f"blocking call {label}(...) while holding lock "
                    f"'{hc.held[-1]}': the critical section now spans the "
                    "wait; move the call outside the lock (snapshot under "
                    "the lock, wait after release)",
                )
        # transitive: a call made under a lock reaches a blocking call
        for qname in sorted(program.summaries):
            s = program.summaries[qname]
            fi = program.functions.get(qname)
            if fi is None:
                continue
            for callee, line, held in s.calls:
                if not held:
                    continue
                for label, chain in self._reach_blocking(
                    program, direct, callee, LOCK_GRAPH_DEPTH - 1, (callee,)
                ):
                    key = (fi.mod.rel, line, label)
                    if key in seen:
                        continue
                    seen.add(key)
                    path = ((qname, fi.mod.rel, line),) + chain
                    yield _program_finding(
                        self,
                        program,
                        fi.mod.rel,
                        line,
                        f"this call reaches blocking {label}(...) while "
                        f"holding lock '{held[-1]}' (via {_fmt_path(path)}); "
                        "the wait happens inside the critical section — "
                        "restructure so the lock is released first",
                    )

    def _direct_blocking(
        self, program: Program
    ) -> dict[str, list[tuple[str, str, int]]]:
        """qname -> [(label, file, line)] of blocking calls in its own body."""
        out: dict[str, list[tuple[str, str, int]]] = {}
        for qname in sorted(program.functions):
            fi = program.functions[qname]
            hits: list[tuple[str, str, int]] = []
            for node in walk_skipping_defs(fi.node.body):
                if isinstance(node, ast.Call):
                    label = blocking_label(fi.mod, node)
                    if label is not None:
                        hits.append((label, fi.mod.rel, node.lineno))
            if hits:
                out[qname] = hits
        return out

    def _reach_blocking(
        self,
        program: Program,
        direct: dict[str, list[tuple[str, str, int]]],
        qname: str,
        depth: int,
        stack: tuple[str, ...],
    ) -> Iterator[tuple[str, tuple[tuple[str, str, int], ...]]]:
        for label, file, line in direct.get(qname, ()):
            yield label, ((qname, file, line),)
        if depth <= 0:
            return
        s = program.summaries.get(qname)
        if s is None:
            return
        fi = program.functions.get(qname)
        file = fi.mod.rel if fi else ""
        for callee, line, _held in s.calls:
            if callee in stack:
                continue
            for label, chain in self._reach_blocking(
                program, direct, callee, depth - 1, stack + (callee,)
            ):
                yield label, ((qname, file, line),) + chain
