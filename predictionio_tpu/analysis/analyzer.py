"""Analyzer driver: walk files, run rules, apply suppressions, render.

Stdlib-``ast`` only — analyzing a tree never imports the analyzed code, so
`pio check` is safe to run on broken or jax-dependent modules from any
environment (the DASE contract checks in ``contract.py`` are the one
deliberate exception: they import engine factories on request).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Sequence

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.pragmas import is_suppressed, pragma_map
from predictionio_tpu.analysis.rules import ALL_RULES, Rule, parse_module

#: directories never descended into during a scan
_SKIP_DIRS = frozenset(
    ("__pycache__", ".git", ".venv", "venv", "node_modules", ".eggs")
)


@dataclass
class AnalysisReport:
    """Findings after pragma suppression (baseline applies later)."""

    findings: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparseable files etc.
    files_scanned: int = 0
    pragma_suppressed: int = 0
    baseline_suppressed: int = 0

    def summary(self) -> dict[str, Any]:
        by_sev: dict[str, int] = {}
        for f in self.findings:
            by_sev[str(f.severity)] = by_sev.get(str(f.severity), 0) + 1
        return {
            "files_scanned": self.files_scanned,
            "total": len(self.findings),
            "by_severity": by_sev,
            "pragma_suppressed": self.pragma_suppressed,
            "baseline_suppressed": self.baseline_suppressed,
            "errors": len(self.errors),
        }


def iter_python_files(paths: Sequence[Path | str]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # skip-dirs are judged relative to the scan root: a repo
                # that happens to live UNDER a directory named venv/ must
                # still scan (only nested venvs inside the tree are skipped)
                if not any(part in _SKIP_DIRS for part in f.relative_to(p).parts):
                    out.append(f)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    # de-dup while preserving order (overlapping path args)
    seen: set[Path] = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def analyze_source(
    source: str,
    rel: str = "<string>",
    path: Path | None = None,
    rules: Iterable[Rule] | None = None,
) -> list[Finding]:
    """Analyze one source string (fixture tests, editor integrations)."""
    mod = parse_module(path or Path(rel), rel, source)
    active = list(rules) if rules is not None else list(ALL_RULES.values())
    pragmas = pragma_map(mod.lines)
    findings: list[Finding] = []
    for r in active:
        findings.extend(
            f for f in r.check(mod) if not is_suppressed(f, pragmas)
        )
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def analyze_paths(
    paths: Sequence[Path | str],
    root: Path | str | None = None,
    rules: Iterable[Rule] | None = None,
) -> AnalysisReport:
    """Run every (or the given) rule over all .py files under ``paths``.

    ``root`` anchors the relative paths used in findings and baseline
    matching; it defaults to the current working directory.
    """
    root = Path(root) if root is not None else Path.cwd()
    active = list(rules) if rules is not None else list(ALL_RULES.values())
    report = AnalysisReport()
    for path in iter_python_files(paths):
        rel = _relpath(path, root)
        try:
            source = path.read_text(encoding="utf-8")
            mod = parse_module(path, rel, source)
        except (OSError, SyntaxError, ValueError) as e:
            report.errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        report.files_scanned += 1
        pragmas = pragma_map(mod.lines)
        for r in active:
            for f in r.check(mod):
                if is_suppressed(f, pragmas):
                    report.pragma_suppressed += 1
                else:
                    report.findings.append(f)
    report.findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return report


def filter_severity(
    findings: Iterable[Finding], threshold: Severity
) -> list[Finding]:
    return [f for f in findings if f.severity >= threshold]


def render_text(report: AnalysisReport) -> str:
    lines = [f.text() for f in report.findings]
    lines += [f"error: {e}" for e in report.errors]
    s = report.summary()
    suppressed = s["pragma_suppressed"] + s["baseline_suppressed"]
    tail = (
        f"{s['total']} finding(s) in {s['files_scanned']} file(s)"
        + (f", {suppressed} suppressed" if suppressed else "")
        + (f", {s['errors']} file error(s)" if s["errors"] else "")
    )
    lines.append(tail)
    return "\n".join(lines)


def render_json(report: AnalysisReport) -> dict[str, Any]:
    return {
        "version": 1,
        "findings": [f.to_json_dict() for f in report.findings],
        "errors": list(report.errors),
        "summary": report.summary(),
    }
