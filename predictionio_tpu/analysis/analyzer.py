"""Analyzer driver: walk files, run rules, apply suppressions, render.

Stdlib-``ast`` only — analyzing a tree never imports the analyzed code, so
`pio check` is safe to run on broken or jax-dependent modules from any
environment (the DASE contract checks in ``contract.py`` are the one
deliberate exception: they import engine factories on request).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Sequence

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.pragmas import is_suppressed, pragma_map
from predictionio_tpu.analysis.rules import (
    ALL_RULES,
    ProgramRule,
    Rule,
    parse_module,
)

#: directories never descended into during a scan
_SKIP_DIRS = frozenset(
    ("__pycache__", ".git", ".venv", "venv", "node_modules", ".eggs")
)


@dataclass
class AnalysisReport:
    """Findings after pragma suppression (baseline applies later)."""

    findings: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)  # unparseable files etc.
    files_scanned: int = 0
    pragma_suppressed: int = 0
    baseline_suppressed: int = 0

    def summary(self) -> dict[str, Any]:
        by_sev: dict[str, int] = {}
        for f in self.findings:
            by_sev[str(f.severity)] = by_sev.get(str(f.severity), 0) + 1
        return {
            "files_scanned": self.files_scanned,
            "total": len(self.findings),
            "by_severity": by_sev,
            "pragma_suppressed": self.pragma_suppressed,
            "baseline_suppressed": self.baseline_suppressed,
            "errors": len(self.errors),
        }


def iter_python_files(paths: Sequence[Path | str]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # skip-dirs are judged relative to the scan root: a repo
                # that happens to live UNDER a directory named venv/ must
                # still scan (only nested venvs inside the tree are skipped)
                if not any(part in _SKIP_DIRS for part in f.relative_to(p).parts):
                    out.append(f)
        else:
            raise FileNotFoundError(f"no such file or directory: {p}")
    # de-dup while preserving order (overlapping path args)
    seen: set[Path] = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def analyze_source(
    source: str,
    rel: str = "<string>",
    path: Path | None = None,
    rules: Iterable[Rule] | None = None,
) -> list[Finding]:
    """Analyze one source string (fixture tests, editor integrations).

    Program rules run over a one-module Program, so single-file fixtures
    exercise them too (cross-module edges obviously need analyze_paths).
    """
    mod = parse_module(path or Path(rel), rel, source)
    active = list(rules) if rules is not None else list(ALL_RULES.values())
    pragmas = pragma_map(mod.lines)
    findings: list[Finding] = []
    program_rules = [r for r in active if isinstance(r, ProgramRule)]
    for r in active:
        findings.extend(
            f for f in r.check(mod) if not is_suppressed(f, pragmas)
        )
    if program_rules:
        from predictionio_tpu.analysis.callgraph import build_program

        program = build_program([mod])
        for r in program_rules:
            findings.extend(
                f
                for f in r.check_program(program)
                if not is_suppressed(f, pragmas)
            )
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def analyze_paths(
    paths: Sequence[Path | str],
    root: Path | str | None = None,
    rules: Iterable[Rule] | None = None,
    cache=None,
) -> AnalysisReport:
    """Run every (or the given) rule over all .py files under ``paths``.

    ``root`` anchors the relative paths used in findings and baseline
    matching; it defaults to the current working directory.

    ``cache`` is an optional :class:`predictionio_tpu.analysis.cache
    .CheckCache`; it is honored only for full-rule-set runs (a subset run
    must not poison entries computed under different rules).  A full hit —
    every file sha plus the program digest — skips parsing entirely; a
    partial hit still parses every file (whole-program rules need all
    ASTs) but reuses hit files' local findings.
    """
    root = Path(root) if root is not None else Path.cwd()
    active = list(rules) if rules is not None else list(ALL_RULES.values())
    local_rules = [r for r in active if not isinstance(r, ProgramRule)]
    program_rules = [r for r in active if isinstance(r, ProgramRule)]
    use_cache = cache is not None and rules is None
    report = AnalysisReport()
    files = iter_python_files(paths)

    loaded: list[tuple[Path, str, str, str]] = []  # (path, rel, source, sha)
    for path in files:
        rel = _relpath(path, root)
        try:
            raw = path.read_bytes()
            source = raw.decode("utf-8")
        except (OSError, ValueError) as e:
            report.errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        sha = ""
        if use_cache:
            from predictionio_tpu.analysis.cache import file_sha

            sha = file_sha(raw)
        loaded.append((path, rel, source, sha))

    cached_entries: dict[str, dict | None] = {}
    if use_cache:
        for _p, rel, _s, sha in loaded:
            cached_entries[rel] = cache.lookup(rel, sha)

    if use_cache and not report.errors:
        fast = _assemble_from_cache(cache, loaded, cached_entries, report)
        if fast is not None:
            return fast

    mods = []
    pragma_maps: dict[str, dict] = {}
    for path, rel, source, sha in loaded:
        try:
            mod = parse_module(path, rel, source)
        except (SyntaxError, ValueError) as e:
            report.errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        report.files_scanned += 1
        mods.append((mod, sha))
        pragmas = pragma_map(mod.lines)
        pragma_maps[rel] = pragmas
        cached = cached_entries.get(rel) if use_cache else None
        if cached is not None:
            for d in cached["findings"]:
                report.findings.append(Finding.from_json_dict(d))
            report.pragma_suppressed += int(cached.get("pragma_suppressed", 0))
            continue
        kept: list[Finding] = []
        suppressed = 0
        for r in local_rules:
            for f in r.check(mod):
                if is_suppressed(f, pragmas):
                    suppressed += 1
                else:
                    kept.append(f)
        report.findings.extend(kept)
        report.pragma_suppressed += suppressed
        if use_cache:
            cache.store(rel, sha, kept, suppressed)

    if program_rules and mods:
        digest = None
        prog_cached = None
        if use_cache and not report.errors:
            from predictionio_tpu.analysis.cache import program_digest

            digest = program_digest([(m.rel, sha) for m, sha in mods])
            prog_cached = cache.lookup_program(digest)
        if prog_cached is not None:
            for d in prog_cached["findings"]:
                report.findings.append(Finding.from_json_dict(d))
            report.pragma_suppressed += int(
                prog_cached.get("pragma_suppressed", 0)
            )
        else:
            from predictionio_tpu.analysis.callgraph import build_program

            program = build_program([m for m, _sha in mods])
            kept = []
            suppressed = 0
            for r in program_rules:
                for f in r.check_program(program):
                    if is_suppressed(f, pragma_maps.get(f.file, {})):
                        suppressed += 1
                    else:
                        kept.append(f)
            report.findings.extend(kept)
            report.pragma_suppressed += suppressed
            if digest is not None:
                cache.store_program(digest, kept, suppressed)
    if use_cache:
        cache.save()
    report.findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return report


def _assemble_from_cache(
    cache,
    loaded: list[tuple[Path, str, str, str]],
    cached_entries: dict[str, dict | None],
    report: AnalysisReport,
) -> AnalysisReport | None:
    """Full-hit fast path: every file and the program entry cached."""
    from predictionio_tpu.analysis.cache import program_digest

    entries = [cached_entries.get(rel) for _p, rel, _s, _sha in loaded]
    digest = program_digest([(rel, sha) for _p, rel, _s, sha in loaded])
    prog = cache.lookup_program(digest)
    if prog is None or any(e is None for e in entries):
        return None
    for e in entries:
        assert e is not None
        for d in e["findings"]:
            report.findings.append(Finding.from_json_dict(d))
        report.pragma_suppressed += int(e.get("pragma_suppressed", 0))
    for d in prog["findings"]:
        report.findings.append(Finding.from_json_dict(d))
    report.pragma_suppressed += int(prog.get("pragma_suppressed", 0))
    report.files_scanned = len(loaded)
    cache.save()
    report.findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return report


def filter_severity(
    findings: Iterable[Finding], threshold: Severity
) -> list[Finding]:
    return [f for f in findings if f.severity >= threshold]


def render_text(report: AnalysisReport) -> str:
    lines = [f.text() for f in report.findings]
    lines += [f"error: {e}" for e in report.errors]
    s = report.summary()
    suppressed = s["pragma_suppressed"] + s["baseline_suppressed"]
    tail = (
        f"{s['total']} finding(s) in {s['files_scanned']} file(s)"
        + (f", {suppressed} suppressed" if suppressed else "")
        + (f", {s['errors']} file error(s)" if s["errors"] else "")
    )
    lines.append(tail)
    return "\n".join(lines)


def render_json(report: AnalysisReport) -> dict[str, Any]:
    return {
        "version": 1,
        "findings": [f.to_json_dict() for f in report.findings],
        "errors": list(report.errors),
        "summary": report.summary(),
    }


#: SARIF severity levels by our Severity (SARIF 2.1.0 §3.27.10)
_SARIF_LEVELS = {"low": "note", "medium": "warning", "high": "error"}


def render_sarif(report: AnalysisReport) -> dict[str, Any]:
    """SARIF 2.1.0 log for CI annotation tooling.

    Deterministic for a given report: rule metadata comes from the shipped
    registry (sorted by id), result order follows the report's findings
    order, and URIs are the report's root-relative posix paths.  Parse
    errors surface as tool-execution notifications (the exit-code contract
    still reports them as 2).
    """
    rule_ids = sorted(ALL_RULES)
    rules = [
        {
            "id": rid,
            "shortDescription": {"text": ALL_RULES[rid].summary},
            "defaultConfiguration": {
                "level": _SARIF_LEVELS[str(ALL_RULES[rid].severity)]
            },
        }
        for rid in rule_ids
    ]
    index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for f in report.findings:
        r: dict[str, Any] = {
            "ruleId": f.rule,
            "level": _SARIF_LEVELS.get(str(f.severity), "warning"),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.file},
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col,
                        },
                    }
                }
            ],
        }
        if f.rule in index:
            r["ruleIndex"] = index[f.rule]
        results.append(r)
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "pio-check",
                        "informationUri": (
                            "https://predictionio-tpu.invalid/docs/"
                            "static_analysis"
                        ),
                        "rules": rules,
                    }
                },
                "results": results,
                "invocations": [
                    {
                        "executionSuccessful": not report.errors,
                        "toolExecutionNotifications": [
                            {"level": "error", "message": {"text": e}}
                            for e in report.errors
                        ],
                    }
                ],
            }
        ],
    }
