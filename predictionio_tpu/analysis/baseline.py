"""Checked-in suppression baseline for `pio check`.

A baseline entry matches a finding by ``(rule, file, source)`` — the
stripped text of the flagged line — NOT by line number, so edits elsewhere
in the file don't invalidate suppressions.  Matching is count-aware: two
identical findings need two identical entries.  Every entry carries a
``justification`` string; the self-gate test rejects empty or TODO ones.
"""

from __future__ import annotations

import json
from collections import Counter
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

from predictionio_tpu.analysis.findings import Finding

#: the file `pio check` auto-discovers in the working directory
DEFAULT_BASELINE_NAME = ".pio-check-baseline.json"

_FORMAT_VERSION = 1


class BaselineError(ValueError):
    """Malformed baseline file."""


@dataclass(frozen=True)
class BaselineEntry:
    rule: str
    file: str
    source: str
    justification: str = ""
    line: int = 0  # informational only; matching ignores it

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.file, self.source)


@dataclass
class Baseline:
    entries: list[BaselineEntry] = field(default_factory=list)
    path: Path | None = None

    @classmethod
    def load(cls, path: Path | str) -> "Baseline":
        path = Path(path)
        try:
            data = json.loads(path.read_text())
        except OSError as e:
            raise BaselineError(f"cannot read baseline {path}: {e}") from e
        except json.JSONDecodeError as e:
            raise BaselineError(f"baseline {path} is not valid JSON: {e}") from e
        if not isinstance(data, dict) or "entries" not in data:
            raise BaselineError(
                f"baseline {path}: expected an object with an 'entries' list"
            )
        entries = []
        for i, raw in enumerate(data["entries"]):
            try:
                entries.append(
                    BaselineEntry(
                        rule=raw["rule"],
                        file=raw["file"],
                        source=raw["source"],
                        justification=raw.get("justification", ""),
                        line=int(raw.get("line", 0)),
                    )
                )
            except (KeyError, TypeError) as e:
                raise BaselineError(
                    f"baseline {path}: entry #{i} malformed: {e}"
                ) from e
        return cls(entries=entries, path=path)

    def filter(
        self, findings: Iterable[Finding]
    ) -> tuple[list[Finding], int]:
        """(non-baselined findings, count suppressed by the baseline)."""
        budget = Counter(e.key for e in self.entries)
        remaining: list[Finding] = []
        suppressed = 0
        for f in findings:
            key = (f.rule, f.file, f.source)
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                suppressed += 1
            else:
                remaining.append(f)
        return remaining, suppressed

    @staticmethod
    def write(
        path: Path | str,
        findings: Iterable[Finding],
        justification: str | None = None,
    ) -> int:
        """Write a baseline covering ``findings``; returns the count.

        New entries get a per-(rule, file) placeholder naming exactly what
        must be justified — the self-gate rejects any ``TODO…``
        justification, so a freshly written baseline is deliberately NOT
        yet acceptable (``pio check --write-baseline`` exits 1 listing the
        entries left to edit).  A refresh must not destroy curation:
        entries whose (rule, file, source) key already exists in the
        target file keep their written justification (duplicate keys carry
        over positionally); unedited placeholders are not curation and do
        not carry.  Synthetic findings (``file`` like ``<engine>``, e.g.
        an unresolvable factory) are never written: their empty source
        would baseline-match every future failure of the same kind.
        """
        carried: dict[tuple[str, str, str], list[str]] = {}
        if Path(path).exists():
            try:
                for e in Baseline.load(path).entries:
                    j = e.justification.strip()
                    if j and not j.lower().startswith("todo"):
                        carried.setdefault(e.key, []).append(e.justification)
            except BaselineError:
                pass  # unreadable old file: rewrite from scratch

        def _justify(f: Finding) -> str:
            pool = carried.get((f.rule, f.file, f.source))
            if pool:
                return pool.pop(0)
            return justification or (
                f"TODO({f.rule}): justify suppression in {f.file}"
            )

        entries = [
            {
                "rule": f.rule,
                "file": f.file,
                "line": f.line,
                "source": f.source,
                "justification": _justify(f),
            }
            for f in sorted(
                findings, key=lambda f: (f.file, f.line, f.rule)
            )
            if not f.file.startswith("<")
        ]
        Path(path).write_text(
            json.dumps(
                {"version": _FORMAT_VERSION, "entries": entries}, indent=2
            )
            + "\n"
        )
        return len(entries)
