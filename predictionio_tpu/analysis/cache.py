"""Check-result cache: memoized `pio check` findings keyed by content hash.

The train/deploy DASE pre-flight (and every CI `pio check`) used to re-parse
the whole package per launch.  This cache stores, under
``$PIO_HOME/check-cache.json``:

  - per-file entries keyed by ``(file sha256, rule-set version)`` holding
    the post-pragma local-rule findings, and
  - one program-level entry keyed by a digest over every ``(path, sha)``
    pair, holding the whole-program (PIO-LOCK/JAX008) findings.

The rule-set version is a hash over the ``analysis/*.py`` sources
themselves, so editing any rule invalidates everything automatically.
When every file and the program digest hit, ``analyze_paths`` skips
parsing entirely; on a partial hit it still parses (program rules need
every AST) but reuses the hit files' local findings.  Entries whose
version no longer matches are evicted on load; the table is LRU-capped.
Persistence is atomic (tmp + fsync + rename) and a corrupt or unreadable
cache degrades to a cold one — the cache can never change findings, only
how fast they arrive.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import Any

from predictionio_tpu.analysis.findings import Finding

DEFAULT_CACHE_NAME = "check-cache.json"

#: LRU cap on per-file entries; generous for a package-sized scan
_MAX_FILES = 8192

_ruleset_version_memo: str | None = None


def ruleset_version() -> str:
    """Hash of the analysis package's own sources — the rule-set version."""
    global _ruleset_version_memo
    if _ruleset_version_memo is None:
        h = hashlib.sha256()
        pkg = Path(__file__).parent
        for f in sorted(pkg.glob("*.py")):
            h.update(f.name.encode())
            try:
                h.update(f.read_bytes())
            except OSError:
                h.update(b"?")
        _ruleset_version_memo = h.hexdigest()[:16]
    return _ruleset_version_memo


def file_sha(source_bytes: bytes) -> str:
    return hashlib.sha256(source_bytes).hexdigest()


def program_digest(entries: list[tuple[str, str]]) -> str:
    """Digest over every (rel path, sha) pair of a scan, order-independent."""
    h = hashlib.sha256()
    for rel, sha in sorted(entries):
        h.update(rel.encode())
        h.update(sha.encode())
    return h.hexdigest()[:16]


class CheckCache:
    """One load/save cycle of the on-disk cache for a single scan."""

    def __init__(self, path: Path | str):
        self.path = Path(path)
        self.hits = 0
        self.misses = 0
        self._dirty = False
        self._clock = 0
        self._files: dict[str, dict[str, Any]] = {}
        self._program: dict[str, Any] | None = None
        self._load()

    def _load(self) -> None:
        try:
            raw = json.loads(self.path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return
        if not isinstance(raw, dict) or raw.get("version") != 1:
            return
        if raw.get("ruleset") != ruleset_version():
            return  # stale rule-set: evict everything
        files = raw.get("files")
        if isinstance(files, dict):
            for k, v in files.items():
                if isinstance(v, dict) and "sha" in v and "findings" in v:
                    self._files[str(k)] = v
                    self._clock = max(self._clock, int(v.get("used", 0)))
        prog = raw.get("program")
        if isinstance(prog, dict) and "digest" in prog:
            self._program = prog

    # -- per-file ------------------------------------------------------------

    def lookup(self, rel: str, sha: str) -> dict[str, Any] | None:
        e = self._files.get(rel)
        if e is None or e.get("sha") != sha:
            self.misses += 1
            return None
        self.hits += 1
        self._clock += 1
        e["used"] = self._clock
        self._dirty = True
        return e

    def store(
        self, rel: str, sha: str, findings: list[Finding], suppressed: int
    ) -> None:
        self._clock += 1
        self._files[rel] = {
            "sha": sha,
            "findings": [f.to_json_dict() for f in findings],
            "pragma_suppressed": suppressed,
            "used": self._clock,
        }
        self._dirty = True

    # -- whole-program -------------------------------------------------------

    def lookup_program(self, digest: str) -> dict[str, Any] | None:
        p = self._program
        if p is None or p.get("digest") != digest:
            return None
        return p

    def store_program(
        self, digest: str, findings: list[Finding], suppressed: int
    ) -> None:
        self._program = {
            "digest": digest,
            "findings": [f.to_json_dict() for f in findings],
            "pragma_suppressed": suppressed,
        }
        self._dirty = True

    # -- persistence ---------------------------------------------------------

    def save(self) -> None:
        if not self._dirty:
            return
        files = self._files
        if len(files) > _MAX_FILES:
            keep = sorted(
                files.items(), key=lambda kv: kv[1].get("used", 0)
            )[-_MAX_FILES:]
            files = dict(keep)
        payload = {
            "version": 1,
            "ruleset": ruleset_version(),
            "files": files,
            "program": self._program,
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=str(self.path.parent), prefix=".check-cache-"
            )
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(payload, fh, separators=(",", ":"))
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            pass  # a cache that cannot persist is just a cold cache
        self._dirty = False

    def stats_line(self) -> str:
        return f"cache: {self.hits} hit(s), {self.misses} miss(es)"
