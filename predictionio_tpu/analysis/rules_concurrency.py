"""Concurrency lints (rule family PIO-CONC*).

Motivating cases come from this codebase's own serving stack: the asyncio
front end (server/aio.py) where one blocking call in an ``async def`` stalls
every connection, the microbatch worker where a polling loop burns a core
and adds latency quantization, and lock-guarded shared state (obs registry,
microbatch queue) where one unlocked writer defeats every locked one.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    Rule,
    ancestors,
    parent,
    resolve_call,
    rule,
    walk_skipping_defs,
)

#: canonical names of calls that block the calling thread
_BLOCKING_CALLS = frozenset(
    (
        "time.sleep",
        "subprocess.run",
        "subprocess.call",
        "subprocess.check_call",
        "subprocess.check_output",
        "subprocess.Popen",
        "os.system",
        "os.popen",
        "os.waitpid",
        "os.wait",
        "urllib.request.urlopen",
        "requests.get",
        "requests.post",
        "requests.put",
        "requests.delete",
        "requests.head",
        "requests.request",
        "socket.create_connection",
    )
)

#: blocking *method* names on arbitrary receivers.  Kept to names that are
#: unambiguous on any receiver: sock.recv/accept and serve_forever.  NOT
#: `.join` — str.join is everywhere and the receiver type is unknowable
#: statically.
_BLOCKING_METHODS = frozenset(("serve_forever", "recv", "accept"))


@rule
class BlockingCallInAsync(Rule):
    """PIO-CONC001: blocking call directly inside an `async def` body."""

    id = "PIO-CONC001"
    severity = Severity.HIGH
    summary = (
        "blocking call inside async def; stalls the event loop — use "
        "asyncio equivalents or run_in_executor"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in walk_skipping_defs(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(parent(node), ast.Await):
                    continue  # awaited calls yield the loop — not blocking
                callee = resolve_call(mod, node)
                method = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else ""
                )
                if callee in _BLOCKING_CALLS or method in _BLOCKING_METHODS:
                    label = callee if callee in _BLOCKING_CALLS else method
                    yield self.finding(
                        mod,
                        node,
                        f"blocking call {label}(...) inside async function "
                        f"{fn.name!r} stalls the event loop for every "
                        "connection; await an asyncio equivalent or push it "
                        "to an executor (loop.run_in_executor)",
                    )


@rule
class BusyWaitPoll(Rule):
    """PIO-CONC002: while-loop polling with time.sleep (busy-wait)."""

    id = "PIO-CONC002"
    severity = Severity.HIGH
    summary = (
        "polling busy-wait (while + time.sleep); use an Event/Condition "
        "wakeup instead"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.While):
                continue
            for sub in walk_skipping_defs(node.body):
                if (
                    isinstance(sub, ast.Call)
                    and resolve_call(mod, sub) == "time.sleep"
                    # a nested while owns its own sleep; report once, at the
                    # innermost loop that contains the call
                    and not any(
                        isinstance(a, ast.While) and a is not node
                        for a in _ancestors_until(sub, node)
                    )
                ):
                    yield self.finding(
                        mod,
                        node,
                        "busy-wait: this loop polls with time.sleep, which "
                        "burns CPU and quantizes wakeup latency to the poll "
                        "interval; wait on a threading.Event/Condition (or "
                        "asyncio.Event) that the producer notifies",
                    )
                    break


def _ancestors_until(node: ast.AST, stop: ast.AST) -> Iterator[ast.AST]:
    for a in ancestors(node):
        if a is stop:
            return
        yield a


#: self-attributes that look like synchronization primitives
_LOCK_ATTR_RE = re.compile(r"^_?(lock|cond|condition|mutex|rlock)$|_lock$|_cond$")

#: threading constructors whose result is a lock-like guard.  The metered
#: wrappers (obs/contention.py) count too: adopting ContendedLock on a hot
#: lock must not silently retire the unlocked-mutation check for the state
#: it guards.
_LOCK_CTORS = frozenset(
    (
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "threading.Semaphore",
        "threading.BoundedSemaphore",
        "predictionio_tpu.obs.contention.ContendedLock",
        "predictionio_tpu.obs.contention.ContendedCondition",
    )
)

#: container methods that mutate their receiver
_MUTATING_METHODS = frozenset(
    (
        "append",
        "appendleft",
        "extend",
        "insert",
        "add",
        "update",
        "pop",
        "popleft",
        "popitem",
        "remove",
        "discard",
        "clear",
        "setdefault",
    )
)


@rule
class UnlockedGuardedMutation(Rule):
    """PIO-CONC003: attribute mutated under a lock in one method, mutated
    without it in another."""

    id = "PIO-CONC003"
    severity = Severity.HIGH
    summary = (
        "lock-guarded attribute mutated outside the lock; one unlocked "
        "writer defeats every locked one"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)

    def _check_class(
        self, mod: ModuleInfo, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        lock_attrs = self._lock_attrs(mod, cls)
        if not lock_attrs:
            return
        guarded: set[str] = set()
        unlocked: list[tuple[str, ast.AST, str]] = []  # (attr, node, method)
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            in_init = item.name == "__init__"
            for attr, node, under_lock in self._mutations(item, lock_attrs):
                if under_lock:
                    guarded.add(attr)
                elif not in_init:
                    unlocked.append((attr, node, item.name))
        for attr, node, method in unlocked:
            if attr in guarded:
                yield self.finding(
                    mod,
                    node,
                    f"self.{attr} is mutated under a lock elsewhere in "
                    f"{cls.name} but written here ({method}) without "
                    "holding it; acquire the same lock (or move the write "
                    "inside the existing critical section)",
                )

    def _lock_attrs(self, mod: ModuleInfo, cls: ast.ClassDef) -> set[str]:
        attrs: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and isinstance(node.value, ast.Call)
                        and resolve_call(mod, node.value) in _LOCK_CTORS
                    ):
                        attrs.add(tgt.attr)
        for node in ast.walk(cls):
            if isinstance(node, ast.With):
                for withitem in node.items:
                    ce = withitem.context_expr
                    if (
                        isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"
                        and _LOCK_ATTR_RE.search(ce.attr)
                    ):
                        attrs.add(ce.attr)
        return attrs

    def _mutations(
        self,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
        lock_attrs: set[str],
    ) -> Iterator[tuple[str, ast.AST, bool]]:
        """(attr, node, under_lock) for every self.<attr> mutation in fn."""
        for node in walk_skipping_defs(fn.body):
            attrs: list[str] = []
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if isinstance(node, ast.Assign):
                    targets = node.targets
                else:  # AugAssign / AnnAssign: one target
                    # a bare annotation (`self.x: int`) binds nothing
                    if isinstance(node, ast.AnnAssign) and node.value is None:
                        continue
                    targets = [node.target]
                for tgt in targets:
                    attrs.extend(_target_attrs(tgt))
            elif isinstance(node, ast.Delete):
                # del self.d[k] mutates the guarded container too
                for tgt in node.targets:
                    attrs.extend(_target_attrs(tgt))
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if node.func.attr in _MUTATING_METHODS:
                    a = _self_attr_target(node.func.value)
                    if a is not None:
                        attrs.append(a)
            for attr in attrs:
                if attr in lock_attrs:
                    continue
                yield attr, node, self._under_lock(node, lock_attrs)

    @staticmethod
    def _under_lock(node: ast.AST, lock_attrs: set[str]) -> bool:
        for anc in ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if isinstance(anc, ast.With):
                for withitem in anc.items:
                    ce = withitem.context_expr
                    if (
                        isinstance(ce, ast.Attribute)
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"
                        and ce.attr in lock_attrs
                    ):
                        return True
        return False


def _target_attrs(tgt: ast.AST):
    """self-attribute names in an assignment target, unpacking tuples/lists
    and starred elements (``self.a, *self.b = ...``)."""
    if isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _target_attrs(elt)
    elif isinstance(tgt, ast.Starred):
        yield from _target_attrs(tgt.value)
    else:
        attr = _self_attr_target(tgt)
        if attr is not None:
            yield attr


def _self_attr_target(tgt: ast.AST) -> str | None:
    """'x' for self.x / self.x[...] / self.x[...][...] targets, else None
    (nested subscript chains unwrap to the root attribute)."""
    while isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if (
        isinstance(tgt, ast.Attribute)
        and isinstance(tgt.value, ast.Name)
        and tgt.value.id == "self"
    ):
        return tgt.attr
    return None


#: constructors of per-tenant serving state.  One instance of any of these
#: parked in a module-level global is shared by every tenant co-resident in
#: the replica — exactly the cross-tenant leak the TenantRegistry exists to
#: prevent (docs/robustness.md#multi-tenancy).  Matched by terminal class
#: name so `QualityMonitor()`, `quality.QualityMonitor()`, and an aliased
#: import all resolve; generic process infrastructure (MetricsRegistry,
#: thread pools, lock witnesses) is deliberately NOT listed — those are
#: process-scoped by design.
_TENANT_STATE_CTORS = frozenset(
    (
        "QualityMonitor",
        "SLOTracker",
        "CostLedger",
        "TokenBucket",
        "DeployedEngine",
        "TenantRegistry",
        "Tenant",
    )
)


def _tenant_state_ctor(mod: ModuleInfo, expr: ast.AST) -> str | None:
    """Terminal class name when expr constructs per-tenant state."""
    if not isinstance(expr, ast.Call):
        return None
    callee = resolve_call(mod, expr)
    name = callee.rsplit(".", 1)[-1]
    return name if name in _TENANT_STATE_CTORS else None


@rule
class ModuleLevelTenantSingleton(Rule):
    """PIO-CONC004: module-level singleton holding per-tenant state.

    Two shapes, both the `default_quality()` pattern family:

    * eager — ``_MONITOR = QualityMonitor()`` at module scope
    * lazy  — a function that does ``global _MONITOR`` and assigns it a
      per-tenant-state constructor result (memoized getter)

    Either way the instance is per-*process*: the moment a replica hosts a
    second tenant, both tenants' quality windows / SLO burn / quota state
    land in the same object.  Per-tenant state must be owned by the
    Tenant/TenantRegistry (or passed in explicitly), never reached through
    a module global.  Function-local and instance-attribute construction
    is fine and not flagged.
    """

    id = "PIO-CONC004"
    severity = Severity.HIGH
    summary = (
        "module-level singleton of per-tenant state; every tenant in the "
        "replica shares it — own it in the TenantRegistry instead"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            cls = _tenant_state_ctor(mod, node.value)
            if cls and any(isinstance(t, ast.Name) for t in node.targets):
                name = next(
                    t.id for t in node.targets if isinstance(t, ast.Name)
                )
                yield self.finding(
                    mod,
                    node,
                    f"module-level {cls} singleton {name!r}: every tenant "
                    "in the replica shares this instance, so one tenant's "
                    "state bleeds into another's; construct it per tenant "
                    "and own it in the TenantRegistry",
                )
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared: set[str] = set()
            for sub in walk_skipping_defs(fn.body):
                if isinstance(sub, ast.Global):
                    declared.update(sub.names)
            if not declared:
                continue
            for sub in walk_skipping_defs(fn.body):
                if not isinstance(sub, ast.Assign):
                    continue
                cls = _tenant_state_ctor(mod, sub.value)
                if cls is None:
                    continue
                hit = next(
                    (
                        t.id
                        for t in sub.targets
                        if isinstance(t, ast.Name) and t.id in declared
                    ),
                    None,
                )
                if hit is not None:
                    yield self.finding(
                        mod,
                        sub,
                        f"lazy module-level {cls} singleton {hit!r} "
                        f"(global in {fn.name!r}): the memoized instance "
                        "is per-process, so co-resident tenants share it; "
                        "construct per-tenant state in the TenantRegistry "
                        "or thread it through explicitly",
                    )
