"""JAX hot-path lints (rule family PIO-JAX*).

The failure modes these catch are the classic TPU-serving ones: a silent
host<->device sync inside the per-query path (each ``.item()`` stalls the
dispatch pipeline), device work at module import (allocates buffers before
the mesh is configured), Python control flow on traced values (TracerBool
errors at first call, or silent recompiles), and per-iteration ``jax.jit``
construction (every wrap is a fresh cache entry — retrace + recompile).
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    ProgramRule,
    Rule,
    ancestors,
    jit_decorator_info,
    parent,
    resolve_call,
    rule,
    walk_skipping_defs,
)

#: DASE serving-surface method names + microbatch dispatch conventions —
#: the functions that run once per query (or per wave) under load.
HOT_FUNCTION_NAMES = frozenset(
    ("predict", "batch_predict", "serve", "supplement")
)
HOT_NAME_FRAGMENTS = ("serve_wave", "batch_fn")

#: calls that force a device->host transfer when applied to a jax array
_SYNC_CALLS = frozenset(
    ("jax.device_get", "numpy.asarray", "numpy.array", "numpy.copy")
)


def _is_hot_function(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    name = fn.name
    return name in HOT_FUNCTION_NAMES or any(
        frag in name for frag in HOT_NAME_FRAGMENTS
    )


def _hot_functions(
    mod: ModuleInfo,
) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _is_hot_function(node):
                yield node


@rule
class HotPathDeviceSync(Rule):
    """PIO-JAX001: implicit device sync inside a serving hot-path function."""

    id = "PIO-JAX001"
    severity = Severity.MEDIUM
    summary = (
        "host sync (.item()/device_get/np.asarray) inside a hot-path "
        "function; sync once per batch, not per query"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in _hot_functions(mod):
            for node in walk_skipping_defs(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                callee = resolve_call(mod, node)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    yield self.finding(
                        mod,
                        node,
                        f".item() in hot-path function {fn.name!r} forces a "
                        "device->host sync per call; pull the batched output "
                        "once (jax.device_get) outside the per-query loop",
                    )
                elif callee in _SYNC_CALLS:
                    yield self.finding(
                        mod,
                        node,
                        f"{callee}(...) in hot-path function {fn.name!r} "
                        "synchronizes device buffers to host; hoist the "
                        "transfer out of the per-query path",
                    )


@rule
class ImportTimeDeviceWork(Rule):
    """PIO-JAX002: jnp/jax.random work executed at module import time."""

    id = "PIO-JAX002"
    severity = Severity.HIGH
    summary = (
        "jax.numpy/jax.random call at module import time; device buffers "
        "allocate before mesh/platform configuration"
    )

    _PREFIXES = ("jax.numpy.", "jax.random.")
    _EXACT = frozenset(("jax.device_put", "jax.devices", "jax.local_devices"))

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in self._import_time_nodes(mod.tree.body):
            if not isinstance(node, ast.Call):
                continue
            callee = resolve_call(mod, node)
            if callee.startswith(self._PREFIXES) or callee in self._EXACT:
                yield self.finding(
                    mod,
                    node,
                    f"{callee}(...) runs at import time: JAX initializes its "
                    "backend and allocates device memory before the "
                    "application configures platforms/mesh; build the value "
                    "lazily inside a function",
                )

    def _import_time_nodes(self, body: list[ast.stmt]) -> Iterator[ast.AST]:
        """Module and class bodies execute at import (at any nesting depth
        under module-level if/try/with); function and lambda bodies do not —
        but their decorators and default arguments DO, so those subtrees are
        still walked.  The `if __name__ == '__main__'` block is exempt."""
        stack: list[ast.AST] = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(node.decorator_list)
                stack.extend(d for d in node.args.defaults)
                stack.extend(d for d in node.args.kw_defaults if d is not None)
                continue
            if isinstance(node, ast.Lambda):
                stack.extend(node.args.defaults)
                stack.extend(d for d in node.args.kw_defaults if d is not None)
                continue
            if isinstance(node, ast.If) and _is_main_guard(node):
                # the guarded body is script-only, but the else arm runs on
                # every import (it IS the non-__main__ case)
                stack.extend(node.orelse)
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


def _is_main_guard(stmt: ast.If) -> bool:
    """True only for the literal ``if __name__ == "__main__":`` shape —
    an ``!=`` (or a different comparand) still executes at import."""
    t = stmt.test
    if not (
        isinstance(t, ast.Compare)
        and len(t.ops) == 1
        and isinstance(t.ops[0], ast.Eq)
    ):
        return False
    sides = (t.left, t.comparators[0])
    return any(
        isinstance(s, ast.Name) and s.id == "__name__" for s in sides
    ) and any(
        isinstance(s, ast.Constant) and s.value == "__main__" for s in sides
    )


#: attribute reads on a traced value that are static (safe to branch on)
_STATIC_ATTRS = frozenset(("shape", "ndim", "dtype", "size"))


@rule
class TracedPythonBranch(Rule):
    """PIO-JAX003: Python if/while on a traced argument inside a jitted fn."""

    id = "PIO-JAX003"
    severity = Severity.HIGH
    summary = (
        "Python control flow on a traced value inside @jit; use lax.cond/"
        "select or mark the argument static"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted, static_names, static_nums = jit_decorator_info(mod, fn)
            if not jitted:
                continue
            args = fn.args.posonlyargs + fn.args.args
            traced = {
                a.arg
                for i, a in enumerate(args)
                if a.arg not in static_names and i not in static_nums
            } | {a.arg for a in fn.args.kwonlyargs if a.arg not in static_names}
            traced.discard("self")
            for node in walk_skipping_defs(fn.body):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                name = _traced_name_in_test(node.test, traced)
                if name is not None:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        mod,
                        node,
                        f"Python `{kind}` on traced argument {name!r} inside "
                        f"jitted function {fn.name!r}: this raises a tracer "
                        "error (or silently recompiles per value); use "
                        "jax.lax.cond/jnp.where or static_argnames",
                    )


def _traced_name_in_test(test: ast.AST, traced: set[str]) -> str | None:
    """First traced param the test depends on concretely, else None.

    Exemptions are scoped to the exact subtree they cover — `y is not None
    and x > 0` exempts only the identity check (and still flags ``x``), and
    an isinstance() call exempts only its own operands, never a traced
    comparison elsewhere in the same compound condition.
    """
    exempt: set[int] = set()
    for node in ast.walk(test):
        concrete = (
            # identity checks are resolved on the Python value, not traced
            isinstance(node, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
        ) or (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "isinstance"
        )
        if concrete:
            exempt.update(id(sub) for sub in ast.walk(node))
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Name)
            and node.id in traced
            and id(node) not in exempt
        ):
            par = parent(node)
            if isinstance(par, ast.Attribute) and par.attr in _STATIC_ATTRS:
                continue
            if (  # len(x) of a traced array is its static leading dim
                isinstance(par, ast.Call)
                and isinstance(par.func, ast.Name)
                and par.func.id == "len"
            ):
                continue
            return node.id
    return None


@rule
class JitConstructionInLoop(Rule):
    """PIO-JAX004: jax.jit(...) wrapped inside a loop body (recompile hazard)."""

    id = "PIO-JAX004"
    severity = Severity.HIGH
    summary = (
        "jax.jit(...) constructed inside a loop; each wrap is a fresh trace "
        "cache — hoist the jitted callable out of the loop"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if resolve_call(mod, node) not in ("jax.jit", "jax.pjit"):
                continue
            for anc in ancestors(node):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # jit built per *call* of an inner fn, not per iter
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                    yield self.finding(
                        mod,
                        node,
                        "jax.jit(...) inside a loop creates a new traced "
                        "callable every iteration (no cache reuse, repeated "
                        "XLA compiles); hoist it out of the loop",
                    )
                    break


#: calls that (re)place data onto devices — correct at bind/load time, a
#: per-wave resharding hazard inside a serving loop body (each call pays a
#: host->device transfer AND may re-lay-out a sharded array every wave)
_PLACEMENT_CALLS = frozenset(("jax.device_put",))
_PLACEMENT_SUFFIXES = (".global_data_array", ".shard_put", ".bind_shards")
_PLACEMENT_NAMES = frozenset(
    ("global_data_array", "shard_put", "bind_shards")
)


@rule
class ReshardInHotLoop(Rule):
    """PIO-JAX006: device placement inside a hot-path loop body."""

    id = "PIO-JAX006"
    severity = Severity.MEDIUM
    summary = (
        "jax.device_put/global_data_array inside a predict/batch_fn loop "
        "body; placement belongs at model bind time, not per wave"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        seen: set[int] = set()
        for fn in _hot_functions(mod):
            for loop in walk_skipping_defs(fn.body):
                if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                    continue
                for node in walk_skipping_defs(loop.body + loop.orelse):
                    if not isinstance(node, ast.Call) or id(node) in seen:
                        continue
                    callee = resolve_call(mod, node)
                    if (
                        callee in _PLACEMENT_CALLS
                        or callee in _PLACEMENT_NAMES
                        or callee.endswith(_PLACEMENT_SUFFIXES)
                    ):
                        seen.add(id(node))
                        yield self.finding(
                            mod,
                            node,
                            f"{callee}(...) inside a loop body of hot-path "
                            f"function {fn.name!r}: every iteration pays a "
                            "host->device transfer and may re-shard the "
                            "array per wave; place arrays once at model "
                            "bind/load time and reuse the device copies",
                        )


#: function-name fragments marking the PRE-FENCE half of a pipelined wave:
#: dispatch_batch (engine async halves), _dispatch_wave (MicroBatcher), any
#: *dispatch* helper on the serving path.  Nested ``def``s inside them (the
#: finalize closures) are the fence region and are exempt — that is exactly
#: where the sync belongs.
_DISPATCH_FRAGMENT = "dispatch"

#: explicit sync spellings that stall the pipeline when they run before the
#: fence (np.asarray/np.array are NOT listed: on host lists they are the
#: normal gather idiom and carry no device sync)
_DISPATCH_SYNC_CALLS = frozenset(
    ("jax.block_until_ready", "jax.device_get")
)


@rule
class DispatchRegionSync(Rule):
    """PIO-JAX007: host sync inside the dispatch (pre-fence) region."""

    id = "PIO-JAX007"
    severity = Severity.MEDIUM
    summary = (
        "block_until_ready/.item()/device_get inside a dispatch-phase "
        "function; the sync belongs at the finalize fence"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _DISPATCH_FRAGMENT not in fn.name:
                continue
            # walk_skipping_defs: nested defs (the finalize closures) are
            # the post-fence region — syncs there are the design
            for node in walk_skipping_defs(fn.body):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"block_until_ready() in dispatch-phase function "
                        f"{fn.name!r} blocks the worker before the fence; "
                        "return the pending result and sync in the "
                        "finalize closure instead",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    yield self.finding(
                        mod,
                        node,
                        f".item() in dispatch-phase function {fn.name!r} "
                        "forces a device->host sync before the fence; "
                        "defer the read to the finalize closure",
                    )
                elif resolve_call(mod, node) in _DISPATCH_SYNC_CALLS:
                    yield self.finding(
                        mod,
                        node,
                        f"{resolve_call(mod, node)}(...) in dispatch-phase "
                        f"function {fn.name!r} synchronizes before the "
                        "fence; the dispatch half must stay non-blocking",
                    )


#: bounded call depth for the transitive hot-path walk; deep enough to see
#: "predict -> _gather -> _pull", shallow enough that utility plumbing far
#: from the seam does not drown the report
JAX008_MAX_DEPTH = 4

#: canonical sync spellings checked transitively.  numpy.asarray/array are
#: deliberately NOT here: two calls below the seam the receiver type is
#: unknowable, and on host lists they are the normal gather idiom (JAX001
#: still flags them inside the hot function itself, where context is local).
_TRANSITIVE_SYNC_CALLS = frozenset(
    ("jax.device_get", "jax.block_until_ready")
)


def _transitive_sync_label(mod: ModuleInfo, node: ast.Call) -> str | None:
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "item" and not node.args:
            return "*.item()"
        if node.func.attr == "block_until_ready":
            return "*.block_until_ready()"
    callee = resolve_call(mod, node)
    if callee in _TRANSITIVE_SYNC_CALLS:
        return callee
    return None


@rule
class TransitiveHotPathSync(ProgramRule):
    """PIO-JAX008: host sync in a helper *reachable* from a serving seam.

    JAX001/JAX007 are local — they see syncs written directly inside
    predict/batch_fn/dispatch_* bodies.  This rule walks the call graph
    from those seams (bounded depth) and re-runs the sync set over every
    reached helper, so a ``.item()`` two calls below ``predict`` no longer
    hides.
    """

    id = "PIO-JAX008"
    severity = Severity.MEDIUM
    summary = (
        "host sync (.item()/device_get/block_until_ready) in a helper "
        "reachable from a hot-path function; the stall hides below the "
        "serving seam"
    )

    def check_program(self, program) -> Iterable[Finding]:
        roots = sorted(
            q
            for q, fi in program.functions.items()
            if _is_hot_function(fi.node) or _DISPATCH_FRAGMENT in fi.name
        )
        reach = program.reachable(roots, JAX008_MAX_DEPTH)
        seen: set[tuple[str, int]] = set()
        for q in sorted(reach):
            chain = reach[q]
            if not chain:
                continue  # a seam itself: JAX001/JAX007 territory
            fi = program.functions[q]
            if _is_hot_function(fi.node) or _DISPATCH_FRAGMENT in fi.name:
                continue  # local rules already watch these by name
            mod = fi.mod
            for node in walk_skipping_defs(fi.node.body):
                if not isinstance(node, ast.Call):
                    continue
                label = _transitive_sync_label(mod, node)
                if label is None:
                    continue
                key = (mod.rel, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                root_fn, _, _ = chain[0]
                via = " -> ".join(fn for fn, _, _ in chain) + f" -> {q}"
                yield Finding(
                    rule=self.id,
                    severity=self.severity,
                    file=mod.rel,
                    line=node.lineno,
                    col=node.col_offset + 1,
                    message=(
                        f"{label} in helper {fi.name!r} is reachable from "
                        f"hot-path seam {root_fn!r} (via {via}, depth "
                        f"{len(chain)}): the device->host sync runs once "
                        "per query even though no hot-named function spells "
                        "it; batch the transfer at the seam's fence instead"
                    ),
                    source=mod.line_text(node.lineno),
                )


@rule
class JitMutableDefault(Rule):
    """PIO-JAX005: jitted function with a mutable (unhashable) default arg."""

    id = "PIO-JAX005"
    severity = Severity.MEDIUM
    summary = (
        "mutable default argument on a jitted function; unhashable if "
        "static, retrace hazard if traced"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            jitted, _, _ = jit_decorator_info(mod, fn)
            if not jitted:
                continue
            defaults = list(fn.args.defaults) + [
                d for d in fn.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        mod,
                        d,
                        f"mutable default argument on jitted function "
                        f"{fn.name!r}: unhashable under static_argnums and a "
                        "per-call retrace hazard when traced; use a tuple or "
                        "None-sentinel",
                    )
