"""Observability lints (rule family PIO-OBS*).

Motivating case: every request that reaches an engine must pass through
the request-lifecycle middleware (``httpd.observe_request`` on the
threaded front end, ``record_request_outcome`` in the async one) — that
is where the latency histogram, the SLO tracker, the flight recorder and
per-request cost attribution all hook in.  A handler that dispatches
``app.handle(req)`` directly creates a dark route: it serves traffic
that never shows up in ``pio_request_latency_seconds``, never trips the
latency alert rules, and bills no cost ledger row — invisible exactly
when it misbehaves.
"""

from __future__ import annotations

import ast
from typing import Iterable

from predictionio_tpu.analysis.findings import Finding, Severity
from predictionio_tpu.analysis.rules import (
    ModuleInfo,
    Rule,
    enclosing_function,
    resolve_call,
    rule,
)

#: middleware entry points; a dispatch inside a function that calls either
#: one is the instrumented path itself, not a bypass of it
_MIDDLEWARE_CALLS = ("observe_request", "record_request_outcome")


def _calls_middleware(fn: ast.AST, mod: ModuleInfo) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = resolve_call(mod, node)
        if callee.rpartition(".")[2] in _MIDDLEWARE_CALLS:
            return True
    return False


@rule
class HandlerBypassesRequestMiddleware(Rule):
    """PIO-OBS005: direct ``.handle(req)`` dispatch outside the
    request-latency middleware."""

    id = "PIO-OBS005"
    severity = Severity.MEDIUM
    summary = (
        "route dispatch bypasses the request-latency middleware; requests "
        "served this way are invisible to metrics, SLO burn, alerts, and "
        "cost attribution"
    )

    def check(self, mod: ModuleInfo) -> Iterable[Finding]:
        # server modules only: that is where HTTP dispatch lives; a
        # .handle() helper on a batch job or CLI tool is not a request path
        if "server" not in mod.rel.replace("\\", "/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # the dispatch spelling is a *call* of someone's .handle;
            # passing the bound method as a reference
            # (``observe_request(app, req, app.handle)``) is the
            # middleware doing its job and never matches here
            if not resolve_call(mod, node).endswith(".handle"):
                continue
            fn = enclosing_function(node)
            wrapped = (
                _calls_middleware(fn, mod)
                if fn is not None
                else _calls_middleware(mod.tree, mod)
            )
            if wrapped:
                continue
            where = f"function {fn.name!r}" if fn is not None else "module level"
            yield self.finding(
                mod,
                node,
                f".handle(...) dispatched directly at {where} without the "
                "request-lifecycle middleware: responses served here skip "
                "the latency histogram, SLO tracking, the flight recorder, "
                "and per-request cost attribution — route through "
                "observe_request(app, req, app.handle) (or call "
                "record_request_outcome after timing the dispatch)",
            )
