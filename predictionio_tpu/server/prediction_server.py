"""Prediction serving (:8000) — the `pio deploy` server.

Route parity with workflow/CreateServer.scala:458-706:

  GET  /              HTML status page (engine info, request count,
                      avg/last serving seconds — CreateServer.scala:415-417)
  POST /queries.json  the hot path (:484): extract query -> supplement ->
                      predict per algorithm -> serve -> optional feedback
                      event -> JSON
  POST /reload        hot-swap to the latest COMPLETED engine instance (:635)
  POST /stop          shut the server down (:643, key-authenticated when an
                      access key is configured)

Where the reference re-trains Unit-persisted models at deploy
(Engine.prepareDeploy:210-232), models here always persist as pytrees and
``load_persistent_model`` re-materializes device arrays — the factors land
TPU-resident once at bind time, and every query runs a jit-compiled scoring
program against them.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import secrets
import threading
import time
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Any, Callable, NamedTuple

from predictionio_tpu.core.base import EngineContext, run_sanity_check
from predictionio_tpu.core.engine import Engine, resolve_engine_factory
from predictionio_tpu.core.persistence import load_models
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import EngineInstance
from predictionio_tpu.data.storage.config import StorageRuntime, get_storage
from predictionio_tpu.lifecycle.canary import CANARY_VARIANT, in_canary_fraction
from predictionio_tpu.lifecycle.generations import (
    CorruptModelError,
    GenerationStore,
)
from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs.costs import (
    CostLedger,
    default_ledger,
    request_cost,
)
from predictionio_tpu.obs.disttrace import note_wave_events
from predictionio_tpu.obs.flight import annotate
from predictionio_tpu.obs.hotpath import (
    WAVE_STAGE_MAP,
    HotPathTracker,
    StageClock,
)
from predictionio_tpu.obs.http import add_observability_routes
from predictionio_tpu.obs.logging import get_request_id
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.provenance import ProvenanceStore
from predictionio_tpu.obs import provenance
from predictionio_tpu.obs.quality import (
    DEFAULT_ENTITY_FIELDS,
    QualityMonitor,
    default_quality,
)
from predictionio_tpu.obs.tracing import trace
from predictionio_tpu.resilience import LoadShed, faults
from predictionio_tpu.resilience.admission import AdmissionController
from predictionio_tpu.resilience.deadline import DeadlineExceeded
from predictionio_tpu.resilience.degrade import degraded_scope
from predictionio_tpu.server.httpd import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    error_response,
    json_response,
    key_matches,
    shed_response,
)
from predictionio_tpu.tenancy import (
    APP_HEADER,
    Tenant,
    TenantRegistry,
    TokenBucket,
)
from predictionio_tpu.utils.params import extract_params

log = logging.getLogger("predictionio_tpu.serving")

#: response headers naming the generation that answered — the swap-
#: atomicity contract: header, body, and the quality log always agree
INSTANCE_HEADER = "X-Pio-Engine-Instance"
VARIANT_HEADER = "X-Pio-Variant"


class Binding(NamedTuple):
    """One generation's immutable serving snapshot.  Every request/wave
    captures exactly one Binding, so a concurrent swap can never hand it a
    torn mix of old algorithms and new models."""

    instance: EngineInstance
    params: Any
    algorithms: list
    models: list
    serving: Any
    role: str  # "live" | "canary"


def _render_prediction(p: Any) -> Any:
    if hasattr(p, "to_json_dict"):
        return p.to_json_dict()
    if dataclasses.is_dataclass(p) and not isinstance(p, type):
        return dataclasses.asdict(p)
    return p


def _extract_query(algorithms, payload: dict) -> Any:
    """JsonExtractor role for queries: the first algorithm's declared
    ``query_class`` (BaseAlgorithm.queryClass:118) drives dataclass
    extraction; engines without one get the raw dict."""
    qcls = next(
        (a.query_class for a in algorithms if getattr(a, "query_class", None)),
        None,
    )
    if qcls is None:
        return payload
    return extract_params(qcls, payload)


@dataclass
class FeedbackConfig:
    """Loop predictions back into the event store (CreateServer.scala:527-589).

    The reference POSTs to the event server over HTTP with an access key; the
    single-VM default here writes through the storage layer directly, keyed by
    app id (resolved from the access key when given).
    """

    enabled: bool = False
    app_id: int | None = None
    access_key: str | None = None
    channel_id: int | None = None


class DeployedEngine:
    """Engine + materialized models for one engine instance, hot-swappable.

    Holds up to TWO bound generations: the **live** one (the legacy
    ``instance/params/algorithms/models/serving`` attributes, kept as plain
    attributes for compatibility) and an optional **canary**.  Every flip
    (swap, promote, rollback) replaces whole attribute sets under one lock;
    readers snapshot a whole :class:`Binding` once per request/wave, so
    in-flight work finishes on the generation it started on and no request
    ever sees a torn model.  The per-generation in-flight counter gives
    ``wait_drained`` — the drain step after a flip retires the loser.
    """

    #: class-level defaults so test stubs built via ``__new__`` (no
    #: __init__) still satisfy every method's attribute reads
    generation_store: GenerationStore | None = None
    _canary_binding: Binding | None = None
    _canary_fraction: float = 0.0
    _drain_cond: threading.Condition | None = None
    entity_fields: tuple[str, ...] = DEFAULT_ENTITY_FIELDS

    def __init__(
        self,
        engine: Engine,
        instance: EngineInstance,
        storage: StorageRuntime,
        ctx: EngineContext | None = None,
        generation_store: GenerationStore | None = None,
    ):
        self.engine = engine
        self.storage = storage
        self.ctx = ctx or EngineContext(storage=storage, mode="serving")
        self.generation_store = generation_store
        self._lock = threading.RLock()
        self._drain_cond = threading.Condition()
        self._inflight: dict[str, int] = {}
        self._bind(instance)

    # -- binding construction ------------------------------------------------

    def load_binding(self, instance: EngineInstance, role: str = "live") -> Binding:
        """Materialize one generation WITHOUT flipping anything — the slow
        half of a swap, done outside the lock so serving never stalls on a
        model load."""
        params = self.engine.params_from_json(_instance_variant(instance))
        persisted = load_models(self.storage.models(), instance.id)
        if persisted is None:
            raise RuntimeError(
                f"no model blob for engine instance {instance.id}"
            )
        models = self.engine.prepare_deploy(
            self.ctx, params, persisted, instance_id=instance.id
        )
        _, _, algos, serving = self.engine.instantiate(params)
        return Binding(instance, params, algos, models, serving, role)

    def _install_live(self, binding: Binding) -> None:
        old_models = getattr(self, "models", None)
        with self._lock:
            self.instance = binding.instance
            self.params = binding.params
            self.algorithms = binding.algorithms
            self.models = binding.models
            self.serving = binding.serving
        # the retired generation's factor caches die with it: a repeat
        # entity's next request gathers from the NEW generation's factors
        # (stale rows can never serve — chaos-asserted byte-identical vs a
        # cold cache)
        if old_models is not None and old_models is not binding.models:
            from predictionio_tpu.parallel import device_cache

            device_cache.invalidate_model_caches(old_models, "swap")

    def _bind(self, instance: EngineInstance) -> None:
        self._install_live(self.load_binding(instance))

    # -- snapshots -----------------------------------------------------------

    def live_binding(self) -> Binding:
        with self._lock:
            return Binding(
                self.instance, getattr(self, "params", None),
                self.algorithms, self.models, self.serving, "live",
            )

    def canary_binding(self) -> Binding | None:
        with self._lock:
            return self._canary_binding

    def canary_split(self) -> tuple[Binding | None, float]:
        with self._lock:
            return self._canary_binding, self._canary_fraction

    @property
    def canary_instance(self) -> EngineInstance | None:
        b = self._canary_binding
        return b.instance if b is not None else None

    @property
    def variant_label(self) -> str:
        return getattr(self.instance, "engine_variant", None) or "default"

    def binding_label(self, binding: Binding) -> str:
        return (
            CANARY_VARIANT if binding.role == "canary" else self.variant_label
        )

    def binding_for_entity(self, entity: str | None) -> Binding:
        """Route one query: canary when one is staged AND the entity
        hashes into its fraction (deterministic per entity), else live."""
        with self._lock:
            canary = self._canary_binding
            fraction = self._canary_fraction
        if canary is not None and in_canary_fraction(entity, fraction):
            return canary
        return self.live_binding()

    def payload_entity(self, payload: Any) -> str | None:
        """The joinable entity id of a query payload (same fields the
        quality joiner keys on)."""
        if isinstance(payload, dict):
            for f in self.entity_fields:
                v = payload.get(f)
                if v is not None:
                    return str(v)
        return None

    # -- in-flight tracking (the drain half of a swap) -----------------------

    def acquire_slot(self, binding: Binding) -> None:
        """Take one in-flight ref on the binding's generation.  Split from
        :meth:`serving_slot` because a pipelined wave acquires on the
        dispatch thread and releases on the finalizer thread — the drain
        refcount must span the whole dispatch→fence window or a swap could
        retire a generation whose wave is still unfenced."""
        cond = self._drain_cond
        if cond is None:  # minimal test stubs: no drain bookkeeping
            return
        iid = binding.instance.id
        with cond:
            self._inflight[iid] = self._inflight.get(iid, 0) + 1

    def release_slot(self, binding: Binding) -> None:
        cond = self._drain_cond
        if cond is None:
            return
        iid = binding.instance.id
        with cond:
            n = self._inflight.get(iid, 1) - 1
            if n <= 0:
                self._inflight.pop(iid, None)
            else:
                self._inflight[iid] = n
            cond.notify_all()

    @contextlib.contextmanager
    def serving_slot(self, binding: Binding):
        self.acquire_slot(binding)
        try:
            yield
        finally:
            self.release_slot(binding)

    def inflight_snapshot(self) -> dict[str, int]:
        """Per-generation in-flight request counts — the drain surface the
        fleet autoscaler polls (via /status.json) before SIGTERMing a
        quiesced replica: zero refcounts means no request would be
        dropped."""
        cond = self._drain_cond
        if cond is None:
            return {}
        with cond:
            return {k: v for k, v in self._inflight.items() if v > 0}

    def wait_drained(self, instance_id: str, timeout: float = 5.0) -> bool:
        """Block until no in-flight request references the generation —
        the ``draining`` step that lets a flip retire the old model."""
        cond = self._drain_cond
        if cond is None:
            return True
        with cond:
            return cond.wait_for(
                lambda: self._inflight.get(instance_id, 0) == 0, timeout
            )

    # -- lifecycle transitions ----------------------------------------------

    def stage_canary(self, instance: EngineInstance, fraction: float) -> None:
        """Bind a staged generation as the canary (built outside the lock,
        flipped under it)."""
        binding = self.load_binding(instance, role="canary")
        with self._lock:
            replaced = self._canary_binding
            self._canary_binding = binding
            self._canary_fraction = fraction
        if replaced is not None:
            from predictionio_tpu.parallel import device_cache

            device_cache.invalidate_model_caches(
                replaced.models, "canary_flip"
            )

    def promote_canary(self) -> EngineInstance:
        """Atomic in-memory flip: the canary becomes live in one lock
        region — a request admitted before the flip finishes on the old
        binding it captured; one admitted after sees only the new one."""
        with self._lock:
            binding = self._canary_binding
            if binding is None:
                raise RuntimeError("no canary generation to promote")
            old = self.instance
            self._install_live(binding._replace(role="live"))
            self._canary_binding = None
            self._canary_fraction = 0.0
        log.info(
            "promoted generation %s (was %s)", binding.instance.id, old.id
        )
        return binding.instance

    def clear_canary(self) -> None:
        with self._lock:
            dropped = self._canary_binding
            self._canary_binding = None
            self._canary_fraction = 0.0
        if dropped is not None:
            from predictionio_tpu.parallel import device_cache

            device_cache.invalidate_model_caches(
                dropped.models, "canary_flip"
            )

    def verify_and_swap(self, instance: EngineInstance) -> None:
        """The gated /reload path: checksum + sanity-verify the candidate,
        THEN commit the manifest, THEN flip — any failure leaves the old
        generation serving untouched.  Raises on refusal."""
        store = self.generation_store
        if store is not None:
            gen = store.get(instance.id)
            if gen is None:
                gen = store.record(instance.id, status="staged")
            store.verify(gen)  # CorruptModelError on checksum mismatch
        binding = self.load_binding(instance)
        for m in binding.models:
            run_sanity_check(m)
        if faults.ACTIVE is not None:
            # the crash-mid-swap seam: chaos plans stall/kill here, BETWEEN
            # verification and the manifest commit — a restart must come
            # back on the still-committed last-good generation
            faults.ACTIVE.check("lifecycle.swap", f"reload {instance.id}")
        old = self.instance
        if store is not None:
            store.promote(instance.id, note="reload")
        self._install_live(binding)
        if old.id != instance.id:
            # idempotent reload of the already-bound instance must not
            # stall behind its own steady traffic
            self.wait_drained(old.id, timeout=5.0)

    def reload_latest(self) -> EngineInstance:
        """Verify + swap to the latest COMPLETED instance (MasterActor
        ReloadServer) — same verification gate as the lifecycle paths."""
        latest = self.storage.engine_instances().get_latest_completed(
            self.instance.engine_id,
            self.instance.engine_version,
            self.instance.engine_variant,
        )
        if latest is None:
            raise RuntimeError("no COMPLETED engine instance to reload")
        self.verify_and_swap(latest)
        return latest

    # -- serving -------------------------------------------------------------

    def extract_query(self, query_payload: dict) -> Any:
        with self._lock:
            algorithms = self.algorithms
        return _extract_query(algorithms, query_payload)

    def predict(self, query: Any) -> tuple[Any, Any]:
        return self.predict_bound(self.live_binding(), query)

    def predict_bound(self, binding: Binding, query: Any) -> tuple[Any, Any]:
        if binding.role == "canary" and faults.ACTIVE is not None:
            faults.ACTIVE.check("canary.predict", binding.instance.id)
        # supplement is the host-side entity gather (recent events, seen
        # filters): marked so the hot-path stage table and wave timelines
        # attribute it instead of folding it into "dispatch"/"other"
        with device_obs.wave_stage("host_gather"):
            query = binding.serving.supplement(query)
        predictions = [
            a.predict(m, query)
            for a, m in zip(binding.algorithms, binding.models)
        ]
        return query, binding.serving.serve(query, predictions)

    def predict_batch(self, queries: list[Any]) -> list[tuple[Any, Any]]:
        return self.predict_batch_bound(self.live_binding(), queries)

    def predict_batch_bound(
        self, binding: Binding, queries: list[Any]
    ) -> list[tuple[Any, Any]]:
        """Serve a coalesced wave of queries in one vectorized
        ``batch_predict`` pass per algorithm — the MicroBatcher target."""
        if binding.role == "canary" and faults.ACTIVE is not None:
            faults.ACTIVE.check("canary.predict", binding.instance.id)
        serving = binding.serving
        with device_obs.wave_stage("host_gather"):
            supplemented = [serving.supplement(q) for q in queries]
        per_algo: list[list[Any]] = []
        for a, m in zip(binding.algorithms, binding.models):
            by_idx = dict(a.batch_predict(m, list(enumerate(supplemented))))
            per_algo.append([by_idx[i] for i in range(len(supplemented))])
        return [
            (q, serving.serve(q, [col[i] for col in per_algo]))
            for i, q in enumerate(supplemented)
        ]

    def dispatch_batch_bound(
        self, binding: Binding, queries: list[Any]
    ) -> Callable[[], list[tuple[Any, Any]]] | None:
        """The ASYNC half of :meth:`predict_batch_bound`: run supplement +
        each algorithm's ``dispatch_batch`` (host gather, h2d, async device
        dispatch — NO blocking) and return a finalize callable that fences,
        reads back, and serves.  Returns None — caller falls back to the
        synchronous path — when any algorithm lacks ``dispatch_batch`` or
        declines the shape, or when a fault plan is active (chaos plans
        exercise the battle-tested sync seams: canary.predict, bisection)."""
        if faults.ACTIVE is not None:
            return None
        # check EVERY algorithm supports async dispatch before dispatching
        # ANY: a mixed engine must not pay gather+h2d+kernel for algorithm
        # 1 only to discard it when algorithm 2 turns out to be sync-only
        # (duplicate device work AND double-counted transfer metrics)
        dispatches = [
            getattr(a, "dispatch_batch", None) for a in binding.algorithms
        ]
        if any(d is None for d in dispatches):
            return None
        serving = binding.serving
        with device_obs.wave_stage("host_gather"):
            supplemented = [serving.supplement(q) for q in queries]
        finalizers: list[Callable[[], list[tuple[int, Any]]]] = []
        for dispatch, m in zip(dispatches, binding.models):
            fin = dispatch(m, list(enumerate(supplemented)))
            if fin is None:
                # shape off this algorithm's async menu; the (possibly)
                # already-dispatched sibling work is simply discarded
                return None
            finalizers.append(fin)

        def finalize() -> list[tuple[Any, Any]]:
            per_algo: list[list[Any]] = []
            for fin in finalizers:
                by_idx = dict(fin())
                per_algo.append(
                    [by_idx[i] for i in range(len(supplemented))]
                )
            return [
                (q, serving.serve(q, [col[i] for col in per_algo]))
                for i, q in enumerate(supplemented)
            ]

        return finalize


# The engine-params JSON shape stored on EngineInstance rows round-trips
# through params_from_json; reconstructing needs the name-keyed dicts.
def _instance_variant(instance: EngineInstance) -> dict[str, Any]:
    def one(raw: str) -> dict[str, Any]:
        d = json.loads(raw or "{}")
        if not d:
            return {}
        ((name, params),) = d.items()
        return {"name": name, "params": params}

    return {
        "datasource": one(instance.datasource_params),
        "preparator": one(instance.preparator_params),
        "algorithms": [
            {"name": name, "params": p}
            for entry in json.loads(instance.algorithms_params or "[]")
            for name, p in entry.items()
        ],
        "serving": one(instance.serving_params),
    }


def create_prediction_server_app(
    deployed: DeployedEngine,
    feedback: FeedbackConfig | None = None,
    on_stop: Callable[[], None] | None = None,
    access_key: str | None = None,
    plugins: "PluginContext | None" = None,
    use_microbatch: bool = False,
    #: waves above ~32 lengthen the tail (a query waits up to two waves);
    #: measured on the serving bench, 32 minimizes concurrent p99
    max_batch: int = 32,
    drain_timeout_s: float = 5.0,
    registry: MetricsRegistry | None = None,
    quality: QualityMonitor | None = None,
    #: queued queries past which /queries.json sheds 503 + Retry-After
    #: (PIO_MAX_QUEUE); None = MicroBatcher's default bound (1024),
    #: 0 or negative = unbounded (the legacy behavior)
    max_queue: int | None = None,
    #: in-flight request cap enforced at admission (PIO_MAX_INFLIGHT);
    #: None disables the cap
    max_inflight: int | None = None,
    #: default per-request time budget in seconds, overridable per request
    #: via the X-Pio-Deadline header (PIO_DEFAULT_DEADLINE_S)
    default_deadline_s: float | None = None,
    #: dispatched-but-unfenced waves the MicroBatcher may run ahead of the
    #: finalize fence (PIO_PIPELINE_DEPTH); 0 = pipelining off (waves
    #: finalize inline on the worker, the pre-PR-13 serial behavior)
    pipeline_depth: int | None = None,
    #: closed-loop model lifecycle (docs/robustness.md#model-lifecycle):
    #: None = env-driven (PIO_LIFECYCLE=1), True/False = explicit; a
    #: pre-built LifecycleController may be passed for tests
    enable_lifecycle: bool | None = None,
    lifecycle: "LifecycleController | None" = None,
    lifecycle_policy: "LifecyclePolicy | None" = None,
    #: start the controller's daemon thread (tests drive tick() directly)
    lifecycle_autostart: bool = True,
    #: alert rules engine + black-box incident recorder (the watch loop,
    #: docs/observability.md#alerting): None = env-driven (PIO_ALERTS,
    #: default on); pre-built instances may be passed for tests
    enable_alerts: bool | None = None,
    alerts: "AlertEvaluator | None" = None,
    incidents: "IncidentRecorder | None" = None,
    #: start the evaluator's daemon thread (tests drive tick() directly)
    alerts_autostart: bool = True,
    #: per-app cost ledger (who costs what, docs/observability.md): None =
    #: the process default on the default registry, the same single-VM
    #: sharing contract as ``quality``
    costs: "CostLedger | None" = None,
    #: decision-provenance ring (docs/observability.md#decision-provenance):
    #: None = a fresh default-capacity store; tests pass sized ones
    provenance_store: ProvenanceStore | None = None,
    #: multi-tenant serving (docs/robustness.md#multi-tenancy): a
    #: TenantRegistry whose resident tenants this replica serves — None
    #: wraps ``deployed`` in a single default tenant (legacy behavior).
    #: With a registry, per-request tenant resolution (X-Pio-App header /
    #: ?app= / access key) routes each query to ITS tenant's engine,
    #: quality monitor, SLO tracker, and cost identity, and the front-end
    #: choke points enforce per-tenant quotas and in-flight caps.
    tenants: "TenantRegistry | None" = None,
) -> HTTPApp:
    import os

    from predictionio_tpu.server.plugins import PluginContext

    app = HTTPApp("predictionserver")
    if max_queue is None and os.environ.get("PIO_MAX_QUEUE"):
        max_queue = int(os.environ["PIO_MAX_QUEUE"])
    if max_inflight is None and os.environ.get("PIO_MAX_INFLIGHT"):
        max_inflight = int(os.environ["PIO_MAX_INFLIGHT"])
    if default_deadline_s is None and os.environ.get("PIO_DEFAULT_DEADLINE_S"):
        default_deadline_s = float(os.environ["PIO_DEFAULT_DEADLINE_S"])
    if pipeline_depth is None:
        pipeline_depth = int(os.environ.get("PIO_PIPELINE_DEPTH", "2"))
    #: the front ends read these (httpd.observe_request / aio): deadline
    #: admission + binding, and the in-flight shed gate
    app.default_deadline_s = default_deadline_s
    if max_inflight is not None:
        app.admission = AdmissionController(
            max_inflight, registry=registry or REGISTRY
        )
    feedback = feedback or FeedbackConfig()
    plugins = plugins or PluginContext.from_env()
    stats = {"request_count": 0, "avg_serving_sec": 0.0, "last_serving_sec": 0.0}
    stats_lock = threading.Lock()
    started_at = datetime.now(tz=timezone.utc)
    registry = registry or REGISTRY
    # the process-default monitor on the default registry (so the event
    # server's feedback joiner sees the same prediction log in a single-VM
    # deployment); an explicit registry gets its own isolated monitor
    if quality is None:
        quality = (
            default_quality()
            if registry is REGISTRY
            else QualityMonitor(registry=registry)
        )
    variant_label = (
        getattr(deployed.instance, "engine_variant", None) or "default"
    )
    # cost-ledger identity: bills key on (app, route, variant); the "app"
    # a prediction server serves is its engine (PIO_COST_APP overrides for
    # multi-replica fleets that want per-tenant names)
    if costs is None:
        costs = (
            default_ledger()
            if registry is REGISTRY
            else CostLedger(registry=registry)
        )
    app.costs = costs
    cost_app = os.environ.get("PIO_COST_APP") or str(
        getattr(deployed.instance, "engine_factory", None)
        or getattr(deployed.instance, "engine_id", None)
        or "engine"
    )

    # -- tenant registry (docs/robustness.md#multi-tenancy) ------------------
    # single-engine deployments wrap ``deployed`` in ONE default tenant so
    # both front ends run the same choke points (quota -> in-flight cap ->
    # per-tenant SLO) whether a replica hosts one engine or ten.  The
    # implicit wrap declares hbm_bytes=0: there is nothing to bin-pack
    # against and the engine is already resident.
    if tenants is None:
        tenants = TenantRegistry(registry=registry)
    if tenants.default is None:
        tenants.admit(
            Tenant(
                cost_app,
                deployed,
                quality=quality,
                cost_name=cost_app,
                hbm_bytes=0,
            )
        )
    default_tenant = tenants.default
    app.tenants = tenants

    def _req_tenant(req: Request) -> Tenant:
        # the front-end gate (httpd.admit_request) stamps req.tenant after
        # the quota/in-flight checks; resolution here only covers callers
        # that drive handlers directly (tests, tooling)
        t = getattr(req, "tenant", None)
        if t is None:
            t = tenants.resolve(req) or default_tenant
        return t

    # -- model lifecycle: generation manifest + canary + controller ----------
    from predictionio_tpu.lifecycle.controller import (
        LifecycleController,
        LifecyclePolicy,
    )

    if enable_lifecycle is None and lifecycle is None:
        enable_lifecycle = os.environ.get("PIO_LIFECYCLE", "").lower() in (
            "1", "on", "true", "yes",
        )
    if lifecycle is None and enable_lifecycle:
        if deployed.generation_store is None:
            log.warning(
                "lifecycle requested but the deployed engine has no "
                "generation store; controller disabled"
            )
        else:
            lifecycle = LifecycleController(
                deployed,
                deployed.generation_store,
                quality=quality,
                policy=lifecycle_policy or LifecyclePolicy.from_env(),
                registry=registry,
            )
    app.lifecycle = lifecycle
    canary_tracker = lifecycle.tracker if lifecycle is not None else None
    if lifecycle is not None and lifecycle_autostart:
        lifecycle.start()

    def _observe_variant(binding_role: str, status: int, dt: float) -> None:
        """Feed the canary guardrail stats (error rate + latency per
        variant) — a no-op rollout-wise until a canary starts."""
        if canary_tracker is not None:
            canary_tracker.observe(binding_role == "canary", status, dt)

    # /readyz: a load balancer should only route here when the model is
    # bound, the MicroBatcher accepts work, and the event store answers
    def _model_loaded() -> bool:
        return getattr(deployed, "models", None) is not None

    def _batcher_ready() -> bool:
        batcher = getattr(app, "microbatcher", None)
        return batcher is None or not batcher.draining

    def _event_store_ready() -> bool:
        storage = getattr(deployed, "storage", None)
        if storage is None:  # no store configured (embedded test engines)
            return True
        return storage.l_events() is not None

    def _storage_breakers_ok() -> bool:
        # an OPEN breaker to any of this runtime's storage daemons flips
        # /readyz: serving may continue (degraded), but operators and load
        # balancers see the dependency outage.  Half-open reads as
        # recovering and does not flip readiness.
        storage = getattr(deployed, "storage", None)
        if storage is None or not hasattr(storage, "breakers"):
            return True
        return all(br.state != "open" for br in storage.breakers())

    # solo-path host-stage attribution (obs/hotpath.py): every fully-served
    # request decomposes into named host stages; /hotpath.json holds the
    # p50/p99-per-stage table at ≥95 % wall-time coverage
    hotpath = HotPathTracker(registry)

    # -- the watch loop: alert rules engine + incident recorder --------------
    # the evaluator ticks the default pack (plus PIO_ALERT_RULES) against
    # this process's registry/SLO/breakers/drift/capacity state on the
    # cheap CPU side; firing transitions snapshot a forensic bundle to
    # disk before the bounded rings rotate the evidence away
    from predictionio_tpu.obs.alerts import AlertEvaluator, WebhookSink
    from predictionio_tpu.obs.incident import IncidentRecorder

    if enable_alerts is None and alerts is None:
        enable_alerts = os.environ.get("PIO_ALERTS", "1").lower() not in (
            "0", "off", "false", "no",
        )
    if alerts is None and enable_alerts:
        if incidents is None:
            incidents = IncidentRecorder(registry=registry, app=app)
        sinks = []
        webhook = os.environ.get("PIO_ALERT_WEBHOOK")
        if webhook:
            sinks.append(WebhookSink(webhook, registry=registry))
        alerts = AlertEvaluator(
            registry=registry,
            app=app,
            interval_s=float(os.environ.get("PIO_ALERT_INTERVAL_S", "5")),
            sinks=sinks,
            incidents=incidents,
        )
    elif alerts is not None and incidents is not None:
        alerts.incidents = incidents
    if alerts is not None:
        alerts.app = app
    if incidents is not None:
        incidents.app = app

    add_observability_routes(
        app,
        registry,
        access_key=access_key,
        readiness={
            "model_loaded": _model_loaded,
            "microbatcher": _batcher_ready,
            "event_store": _event_store_ready,
            "storage_breakers": _storage_breakers_ok,
        },
        quality=quality,
        hotpath=hotpath,
        alerts=alerts,
        incidents=incidents,
        costs=costs,
        provenance=provenance_store,
        tenants=tenants,
    )
    # the evaluator daemon starts when a server actually starts serving
    # (AppServer/AsyncAppServer honor this flag), NOT at app construction:
    # a process that builds many apps (tests, tooling) must not accumulate
    # one idle watcher thread per app — sys._current_frames()-walking
    # surfaces (the stack sampler) pay per live thread
    app.alerts_autostart = alerts is not None and alerts_autostart
    m_latency = registry.histogram(
        "pio_request_latency_seconds",
        "Serving request latency by route and status",
        labelnames=("route", "status"),
    )

    def _observe(route: str, status: int, t0: float) -> float:
        dt = time.perf_counter() - t0
        m_latency.labels(route, str(status)).observe(dt)
        return dt

    if feedback.enabled and feedback.app_id is None:
        if not feedback.access_key:
            raise RuntimeError(
                "feedback requires an app_id or access_key to route events"
            )
        k = deployed.storage.access_keys().get(feedback.access_key)
        if k is None:
            raise RuntimeError("feedback access key is invalid")
        feedback.app_id = k.appid

    def _feedback_event(query: Any, rendered_prediction: Any) -> None:
        pr_id = secrets.token_hex(32)
        ev = Event(
            event="predict",
            entity_type="pio_pr",
            entity_id=pr_id,
            properties=DataMap(
                {
                    "engineInstanceId": deployed.instance.id,
                    "query": _render_prediction(query),
                    "prediction": rendered_prediction,
                }
            ),
        )
        deployed.storage.l_events().insert(
            ev, feedback.app_id, feedback.channel_id
        )

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        inst = deployed.instance
        body = f"""<html><head><title>PredictionIO-TPU server</title></head>
<body>
<h1>Engine is deployed and running</h1>
<table>
<tr><td>Engine instance</td><td>{inst.id}</td></tr>
<tr><td>Engine</td><td>{inst.engine_factory or inst.engine_id}</td></tr>
<tr><td>Variant</td><td>{inst.engine_variant}</td></tr>
<tr><td>Started</td><td>{started_at.isoformat()}</td></tr>
<tr><td>Requests</td><td>{stats['request_count']}</td></tr>
<tr><td>Average serving (s)</td><td>{stats['avg_serving_sec']:.6f}</td></tr>
<tr><td>Last serving (s)</td><td>{stats['last_serving_sec']:.6f}</td></tr>
</table>
</body></html>"""
        return Response(200, body)

    @app.route("GET", "/status\\.json")
    def status(req: Request) -> Response:
        batcher = getattr(app, "microbatcher", None)
        return json_response(
            200,
            {
                "status": "alive",
                "engineInstanceId": deployed.instance.id,
                "startTime": started_at.isoformat(),
                # the fleet drain surface: a quiesced replica is safe to
                # stop when no generation holds an in-flight request and
                # the micro-batch queue is idle
                "inflightGenerations": deployed.inflight_snapshot(),
                "batcherBusy": bool(batcher is not None and batcher.busy),
                "apps": tenants.apps(),
                **stats,
            },
        )

    # bad query JSON/shape -> 400; engine/server faults -> logged 500
    # (the reference's MappingException / Throwable split,
    # CreateServer.scala:607-630)
    def _parse_query(req: Request, dep=None):
        payload = req.json()
        if not isinstance(payload, dict):
            raise ValueError("query must be a JSON object")
        return payload, (dep or deployed).extract_query(payload)

    def _finish_query(
        tenant, payload, query, prediction, t0: float, binding=None
    ) -> Response:
        return _finish_rendered(
            tenant, payload, query, _render_prediction(prediction), t0, binding
        )

    def _finish_rendered(
        tenant, payload, query, rendered, t0: float, binding=None
    ) -> Response:
        dep = tenant.deployed
        instance_id = (
            binding.instance.id if binding is not None else dep.instance.id
        )
        answered_variant = (
            dep.binding_label(binding)
            if binding is not None
            else dep.variant_label
        )
        rendered = plugins.process_output(instance_id, payload, rendered)
        if feedback.enabled and feedback.app_id is not None:
            try:
                _feedback_event(query, rendered)
            except Exception as e:  # feedback must never fail the query
                log.error("feedback event failed: %s", e)
        dt = _observe("/queries.json", 200, t0)
        _observe_variant(
            "canary" if answered_variant == CANARY_VARIANT else "live",
            200, dt,
        )
        with stats_lock:
            n = stats["request_count"]
            stats["avg_serving_sec"] = (stats["avg_serving_sec"] * n + dt) / (n + 1)
            stats["last_serving_sec"] = dt
            stats["request_count"] = n + 1
        (tenant.quality or quality).observe_prediction(
            get_request_id(), payload, rendered, variant=answered_variant
        )
        # the decision record keeps what was actually returned — item ids
        # with raw scores — so `pio replay-request` has bits to diff
        provenance.note_answer(rendered)
        resp = json_response(200, rendered)
        resp.headers[INSTANCE_HEADER] = instance_id
        resp.headers[VARIANT_HEADER] = answered_variant
        resp.headers[APP_HEADER] = tenant.name
        return resp

    if use_microbatch:
        from predictionio_tpu.server.microbatch import (
            MicroBatcher,
            PendingWave,
        )

        def _postprocess(dep, payload, query, prediction):
            """Render + plugins + feedback — the blocking tail, on the
            worker thread so the event loop stays free for I/O."""
            rendered = plugins.process_output(
                dep.instance.id, payload, _render_prediction(prediction)
            )
            if feedback.enabled and feedback.app_id is not None:
                try:
                    _feedback_event(query, rendered)
                except Exception as e:  # feedback must never fail the query
                    log.error("feedback event failed: %s", e)
            return rendered

        def _predict_bisect(dep, binding, parsed, idxs, out, depth=0):
            """Batched predict with bisection fault isolation: a failing
            wave splits in half and each half retries batched, so P poison
            queries cost O(P log B) extra dispatches instead of turning the
            whole wave into O(B) solo predicts.  The whole recursion runs
            against ONE captured binding — a swap mid-wave cannot mix
            generations inside a wave."""
            try:
                results = dep.predict_batch_bound(
                    binding, [parsed[i][1] for i in idxs]
                )
            except DeadlineExceeded:
                # the wave's bound budget (its TIGHTEST member's) ran out:
                # not a poison query, so don't bisect — and don't fail the
                # wave-mates, whose own budgets may be fine.  Re-raising
                # hands the wave to the MicroBatcher's solo-retry pass,
                # which re-runs each item under ITS OWN deadline: only
                # genuinely-expired items 504
                raise
            except Exception as e:
                if len(idxs) == 1:
                    out[idxs[0]] = ("err", e, ())
                    return
                if depth == 0:
                    log.exception(
                        "wave predict failed; bisecting to isolate"
                    )
                mid = len(idxs) // 2
                _predict_bisect(dep, binding, parsed, idxs[:mid], out, depth + 1)
                _predict_bisect(dep, binding, parsed, idxs[mid:], out, depth + 1)
                return
            for i, (q, pred) in zip(idxs, results):
                out[i] = ("pred", (q, pred))

        def _serve_wave(items):
            """One wave, split at the fence (docs/performance.md).

            The DISPATCH half runs here on the worker thread: extract,
            canary partition, entity gather + h2d + async device dispatch
            per binding partition (``dispatch_batch_bound``) — nothing
            blocks, so the worker is free to dispatch wave N+1 the moment
            this returns.  The FINALIZE half rides the returned
            :class:`PendingWave` onto the MicroBatcher's finalizer thread:
            fence + d2h + serve + render/plugins/feedback.  Per item the
            final result is one of ("ok", rendered, degraded, route) |
            ("bad", err, (), route) -> 400 | ("err", err, (), route) ->
            500, where ``route`` is the ``(engine instance id, variant
            label)`` that answered — the canary split partitions the wave
            per binding, each partition serving whole against its own
            captured generation (slots held from dispatch to fence, so a
            swap cannot retire a generation with an unfenced wave).  A
            partition whose engines lack async dispatch (or whose dispatch
            fails) computes synchronously in the finalize half — still off
            the worker's critical path — with the bisection fault
            isolation unchanged: a poison query degrades only itself.

            Multi-tenancy: the batcher carries ``(tenant, payload)``
            items, so one wave may span tenants.  The wave partitions
            first by tenant, then by that tenant's live/canary split —
            each tenant's bindings are captured ONCE per wave (swap
            atomicity holds per tenant), and every partition dispatches,
            fences, bills, and releases against ITS tenant's engine.  A
            neighbor's poison query or corrupt generation therefore fails
            only its own partition."""
            wave_tenants: list[Any] = []
            for t, _ in items:
                if not any(t is wt for wt in wave_tenants):
                    wave_tenants.append(t)
            payloads = [pl for _, pl in items]
            # (live, canary, fraction) per tenant, captured once per wave
            splits: dict[int, tuple[Any, Any, float]] = {}
            for t in wave_tenants:
                live_b = t.deployed.live_binding()
                canary_b, fraction = t.deployed.canary_split()
                splits[id(t)] = (live_b, canary_b, fraction)
            bindings: list[Any] = []
            for t, pl in items:
                live_b, canary_b, fraction = splits[id(t)]
                b = live_b
                if canary_b is not None and in_canary_fraction(
                    t.deployed.payload_entity(pl), fraction
                ):
                    b = canary_b
                bindings.append(b)
            routes = [
                (b.instance.id, t.deployed.binding_label(b))
                for (t, _), b in zip(items, bindings)
            ]
            # the decision record's identity half, once per binding (the
            # generation lookup is memoized); engine-side detail collects
            # per partition through the wave-scoped provenance collector
            # (the request scope is invisible on worker/finalizer threads)
            base_prov: dict[int, dict[str, Any]] = {}
            for t in wave_tenants:
                live_b, canary_b, _fr = splits[id(t)]
                for b in (live_b, canary_b):
                    if b is not None:
                        base_prov[id(b)] = dict(
                            provenance.binding_fields(t.deployed, b),
                            app=t.name,
                        )
            part_notes: dict[int, dict[str, Any]] = {}

            def _merge_wave_notes(b, wtoken) -> None:
                collected = provenance.end_wave(wtoken)
                deep = collected.pop("_deep", None)
                notes = part_notes.setdefault(id(b), {})
                notes.update(collected)
                if deep:
                    notes.setdefault("_deep", {}).update(deep)

            parsed: list[tuple[str, Any]] = []
            partitions: list[tuple[Any, Any, list[int], Any]] = []
            with degraded_scope() as degraded:
                for t, pl in items:
                    try:
                        parsed.append(("q", t.deployed.extract_query(pl)))
                    except Exception as e:
                        parsed.append(("bad", e))
                out: list[Any] = [(tag, v, ()) for tag, v in parsed]
                for t in wave_tenants:
                    live_b, canary_b, _fr = splits[id(t)]
                    for b in (live_b, canary_b):
                        if b is None:
                            continue
                        ok_idx = [
                            i for i, (tag, _) in enumerate(parsed)
                            if tag == "q" and bindings[i] is b
                        ]
                        if not ok_idx:
                            continue
                        dep = t.deployed
                        dep.acquire_slot(b)
                        fin = None
                        wtoken = provenance.begin_wave()
                        try:
                            fin = dep.dispatch_batch_bound(
                                b, [parsed[i][1] for i in ok_idx]
                            )
                        except Exception:
                            # dispatch failed before the fence: the
                            # finalize half re-runs this partition
                            # synchronously with bisection, which
                            # attributes the real poison
                            log.exception(
                                "async wave dispatch failed; partition "
                                "falls back to the synchronous path"
                            )
                            fin = None
                        finally:
                            _merge_wave_notes(b, wtoken)
                        partitions.append((t, b, ok_idx, fin))
                degraded_pre = tuple(degraded)

            def _finalize():
                remaining = list(partitions)
                try:
                    with degraded_scope() as degraded:
                        while remaining:
                            t, b, ok_idx, fin = remaining[0]
                            dep = t.deployed
                            wtoken = provenance.begin_wave()
                            try:
                                if fin is None:
                                    _predict_bisect(
                                        dep, b, parsed, ok_idx, out
                                    )
                                else:
                                    try:
                                        results = fin()
                                    except DeadlineExceeded:
                                        # wave budget ran out at the fence:
                                        # hand the wave to the solo-retry
                                        # pass (per-item deadlines), same
                                        # as the sync path
                                        raise
                                    except Exception:
                                        log.exception(
                                            "async wave finalize failed; "
                                            "bisecting to isolate"
                                        )
                                        _predict_bisect(
                                            dep, b, parsed, ok_idx, out
                                        )
                                    else:
                                        for i, (q, pred) in zip(
                                            ok_idx, results
                                        ):
                                            out[i] = ("pred", (q, pred))
                            finally:
                                _merge_wave_notes(b, wtoken)
                                dep.release_slot(b)
                                remaining.pop(0)
                        for i, entry in enumerate(out):
                            if entry[0] != "pred":
                                continue
                            q, pred = entry[1]
                            try:
                                out[i] = (
                                    "ok",
                                    _postprocess(
                                        items[i][0].deployed,
                                        payloads[i], q, pred,
                                    ),
                                    (),
                                )
                            except Exception as e:  # plugin error: only
                                out[i] = ("err", e, ())  # this item fails
                        deg = degraded_pre + tuple(
                            d for d in degraded if d not in degraded_pre
                        )
                except BaseException:
                    for t, b, _, _ in remaining:
                        t.deployed.release_slot(b)
                    raise

                def _prov_item(i: int) -> dict[str, Any]:
                    b = bindings[i]
                    d = dict(base_prov.get(id(b)) or {})
                    notes = part_notes.get(id(b))
                    if notes:
                        d.update(notes)
                    return d

                return [
                    (
                        entry[0],
                        entry[1],
                        deg if entry[0] == "ok" else (),
                        routes[i],
                        _prov_item(i),
                    )
                    for i, entry in enumerate(out)
                ]

            if all(fin is None for _, _, _, fin in partitions):
                # nothing dispatched async (host-replica or sharded
                # engines): compute inline on the worker thread — keeping
                # the worker busy is what lets queue pressure coalesce the
                # next wave (natural batching), so these waves must NOT
                # ride the pipeline
                return _finalize()
            return PendingWave(_finalize)

        batcher = MicroBatcher(
            _serve_wave,
            max_batch=max_batch,
            drain_timeout_s=drain_timeout_s,
            registry=registry,
            max_inflight_waves=pipeline_depth,
            # None -> the batcher's default bound; 0/negative -> unbounded
            **(
                {"max_queue": max_queue if max_queue > 0 else None}
                if max_queue is not None
                else {}
            ),
        )
        app.microbatcher = batcher  # exposed for tests/status introspection

        def _bump_stats(t0: float) -> None:
            dt = _observe("/queries.json", 200, t0)
            with stats_lock:
                n = stats["request_count"]
                stats["avg_serving_sec"] = (
                    stats["avg_serving_sec"] * n + dt
                ) / (n + 1)
                stats["last_serving_sec"] = dt
                stats["request_count"] = n + 1

        @app.route("POST", "/queries\\.json")
        async def queries(req: Request) -> Response:
            t0 = time.perf_counter()
            clock = StageClock()
            try:
                payload = req.json()
                if not isinstance(payload, dict):
                    raise ValueError("query must be a JSON object")
            except Exception as e:
                _observe("/queries.json", 400, t0)
                return error_response(400, f"invalid query: {e}")
            clock.lap("parse")
            tenant = _req_tenant(req)
            t_variant = tenant.deployed.variant_label
            # the worker fills meta with this query's queue-wait/device
            # split + wave mates; annotate() hands it to the flight recorder
            meta: dict[str, Any] = {}
            route_info: tuple[str, str] | None = None
            prov_item: dict[str, Any] | None = None
            try:
                with trace("serve.microbatch", record=False) as mb_span:
                    clock.lap("route")
                    status, value, degraded, route_info, prov_item = (
                        await batcher.submit((tenant, payload), meta)
                    )
                    # decompose the await window: queued wait + the wave's
                    # device-stage split, leftover = loop wakeup + future
                    # resolution (the "block until ready" tail)
                    parts = {"queue_wait": meta.get("queue_wait_s") or 0.0}
                    for key, seconds in (
                        meta.get("device_breakdown") or {}
                    ).items():
                        stage = WAVE_STAGE_MAP.get(key, key)
                        parts[stage] = parts.get(stage, 0.0) + seconds
                    clock.split(parts, remainder="block_until_ready")
                    # the wave's device-stage + per-shard events become
                    # device-track fragments of THIS request's trace,
                    # parented under the serve span (obs/disttrace.py)
                    note_wave_events(meta, parent=mb_span)
            except LoadShed as e:
                # bounded queue: shed instead of letting the backlog grow —
                # clients get an honest 503 + Retry-After
                _observe("/queries.json", 503, t0)
                costs.note_shed(tenant.cost_name, "/queries.json", t_variant)
                return shed_response(str(e), e.retry_after_s)
            except DeadlineExceeded as e:
                # the budget ran out while queued (or mid-wave): no point
                # answering a client that already gave up — but the queue
                # seconds it held were real, so they still bill
                _observe("/queries.json", 504, t0)
                costs.bill_meta(
                    tenant.cost_name, "/queries.json", t_variant, meta,
                    queue_only=True,
                )
                return error_response(504, f"deadline exceeded: {e}")
            except Exception as e:
                log.exception("query serving failed")
                _observe("/queries.json", 500, t0)
                return error_response(500, f"{type(e).__name__}: {e}")
            finally:
                if meta:
                    annotate(**meta)
            instance_id, answered_variant = route_info or (
                tenant.deployed.instance.id, t_variant,
            )
            # the decision record: the wave item's binding identity +
            # engine notes, the wave coordinates, and the cache split —
            # the same facts the response headers and quality log assert
            if prov_item:
                deep_part = prov_item.pop("_deep", None)
                provenance.note(**prov_item)
                if deep_part:
                    provenance.note_deep(**deep_part)
            provenance.note(payload=payload)
            wave_info = {
                key[len("wave_"):]: meta[key]
                for key in ("wave_id", "wave_size", "wave_seq")
                if meta.get(key) is not None
            }
            if wave_info:
                provenance.note(wave=wave_info)
            if meta.get("cache_hits") or meta.get("cache_misses"):
                provenance.note(
                    cache={
                        "hits": meta.get("cache_hits", 0),
                        "misses": meta.get("cache_misses", 0),
                        "generation": instance_id,
                    }
                )
            if meta.get("wave_request_ids"):
                provenance.note_deep(
                    wave_request_ids=meta["wave_request_ids"]
                )
            if degraded:
                provenance.note(degraded=list(degraded))
            # header == flight == provenance == quality: the flight entry
            # names the answering generation too, so the four-way agreement
            # is checkable from any one surface
            annotate(instance_id=instance_id, variant=answered_variant)
            # bill the prorated wave share to (app, route, variant) — every
            # answered status, 400/500 included: the wave computed for this
            # member either way, and conservation (ledger sums == aggregate
            # device counters) only holds if every share lands somewhere
            costs.bill_meta(
                tenant.cost_name, "/queries.json", answered_variant, meta
            )
            def _stamped(resp: Response) -> Response:
                resp.headers[INSTANCE_HEADER] = instance_id
                resp.headers[VARIANT_HEADER] = answered_variant
                resp.headers[APP_HEADER] = tenant.name
                return resp

            if status == "bad":
                _observe("/queries.json", 400, t0)
                _observe_variant(
                    "canary" if answered_variant == CANARY_VARIANT else "live",
                    400, time.perf_counter() - t0,
                )
                return _stamped(error_response(400, f"invalid query: {value}"))
            if status == "err":
                log.error("query serving failed: %s", value)
                _observe("/queries.json", 500, t0)
                _observe_variant(
                    "canary" if answered_variant == CANARY_VARIANT else "live",
                    500, time.perf_counter() - t0,
                )
                return _stamped(error_response(
                    500, f"{type(value).__name__}: {value}"
                ))
            _bump_stats(t0)
            _observe_variant(
                "canary" if answered_variant == CANARY_VARIANT else "live",
                200, time.perf_counter() - t0,
            )
            (tenant.quality or quality).observe_prediction(
                get_request_id(),
                payload,
                value,
                variant=answered_variant,
                wave_size=meta.get("wave_size"),
                wave_seq=meta.get("wave_seq"),
            )
            # the decision record keeps what was actually returned — item
            # ids with raw scores — so `pio replay-request` has bits to diff
            provenance.note_answer(value)
            # the swap-atomicity contract: the generation that answered is
            # stamped on the response and matches the variant the quality
            # log recorded for this request id
            resp = _stamped(json_response(200, value))
            if degraded:
                # answered from model-only fallback (event store down/over
                # budget): correct-but-degraded, stamped so clients and
                # probes can tell (metrics carry pio_degraded_total)
                resp.headers["X-Pio-Degraded"] = ",".join(degraded)
            # encode NOW (memoized — the front end reuses it) so the JSON
            # serialization cost lands in the serialize stage, then close
            # the attribution window
            resp.encoded()
            clock.lap("serialize")
            hotpath.observe_clock(clock)
            return resp

    else:

        @app.route("POST", "/queries\\.json")
        def queries(req: Request) -> Response:
            # the whole solo path runs on this thread, so one bound
            # RequestCost catches its storage reads directly; the predict
            # window's measured device time + XLA cost bill on exit
            tenant = _req_tenant(req)
            with request_cost(
                tenant.cost_name, "/queries.json",
                tenant.deployed.variant_label, ledger=costs,
            ) as cost_rec:
                return _solo_query(req, tenant, cost_rec)

        def _solo_query(req: Request, tenant, cost_rec) -> Response:
            t0 = time.perf_counter()
            clock = StageClock()
            dep = tenant.deployed

            def _stamped(resp: Response, binding=None) -> Response:
                # every answer — errors included — names the generation
                # that (would have) answered, so 5xx attribution works
                # exactly when it matters most
                resp.headers[INSTANCE_HEADER] = (
                    binding.instance.id if binding else dep.instance.id
                )
                resp.headers[VARIANT_HEADER] = (
                    dep.binding_label(binding)
                    if binding
                    else dep.variant_label
                )
                resp.headers[APP_HEADER] = tenant.name
                return resp

            try:
                payload, query = _parse_query(req, dep)
            except Exception as e:
                _observe("/queries.json", 400, t0)
                return _stamped(error_response(400, f"invalid query: {e}"))
            clock.lap("parse")
            binding = dep.binding_for_entity(
                dep.payload_entity(payload)
            )
            cost_rec.variant = dep.binding_label(binding)
            # the decision record's identity half: payload + generation +
            # hash-side (memoized manifest read — cheap-capture budget)
            provenance.note(
                payload=payload,
                app=tenant.name,
                **provenance.binding_fields(dep, binding),
            )
            annotate(
                instance_id=binding.instance.id,
                variant=dep.binding_label(binding),
            )
            clock.lap("route")
            try:
                with dep.serving_slot(binding), degraded_scope() as degraded:
                    # the wave timeline collects the engine's stage marks
                    # (supplement's host_gather, any device h2d/compute/d2h)
                    # so the predict window splits into named stages; the
                    # unattributed interior is "dispatch"
                    timeline = None
                    t_pred = time.perf_counter()
                    try:
                        with device_obs.wave_timeline() as timeline:
                            query, prediction = dep.predict_bound(
                                binding, query
                            )
                    finally:
                        # solo device_s is the predict window — the same
                        # bracket the MicroBatcher's wave device_s draws
                        # around batch_fn (billed on error paths too: the
                        # compute happened)
                        cost_rec.add(
                            device_s=time.perf_counter() - t_pred
                        )
                        if timeline is not None:
                            cost_rec.add(
                                flops=timeline.flops,
                                hbm_bytes=timeline.bytes,
                                storage_bytes=timeline.storage_bytes,
                                cache_hits=timeline.cache_hits,
                                cache_misses=timeline.cache_misses,
                            )
                            # factor-cache provenance: the cache lives and
                            # dies with the serving generation, so its
                            # "generation" IS the bound instance id
                            provenance.note(
                                cache={
                                    "hits": timeline.cache_hits,
                                    "misses": timeline.cache_misses,
                                    "generation": binding.instance.id,
                                }
                            )
            except DeadlineExceeded as e:
                _observe("/queries.json", 504, t0)
                return _stamped(
                    error_response(504, f"deadline exceeded: {e}"), binding
                )
            except Exception as e:
                log.exception("query serving failed")
                _observe("/queries.json", 500, t0)
                _observe_variant(
                    binding.role, 500, time.perf_counter() - t0
                )
                return _stamped(
                    error_response(500, f"{type(e).__name__}: {e}"), binding
                )
            clock.split(
                {
                    WAVE_STAGE_MAP.get(k, k): v
                    for k, v in timeline.stages.items()
                },
                remainder="dispatch",
            )
            if degraded:
                provenance.note(degraded=list(degraded))
            resp = _finish_query(tenant, payload, query, prediction, t0, binding)
            if degraded:
                resp.headers["X-Pio-Degraded"] = ",".join(degraded)
            resp.encoded()
            clock.lap("serialize")
            hotpath.observe_clock(clock)
            return resp

    def _authorized(req: Request) -> bool:
        # Bearer header or ?accessKey= — the same contract as the other
        # mutating/debug routes (obs/http.py)
        return access_key is None or key_matches(req, access_key)

    @app.route("POST", "/reload")
    def reload(req: Request) -> Response:
        """Hot-swap to the latest COMPLETED instance — gated behind the
        generation manifest: the candidate's blob checksum and
        ``sanity_check()`` run BEFORE the flip, and any refusal answers
        409 with the reason while the old generation keeps serving.
        Tenant-scoped: ``?app=`` / the X-Pio-App header picks WHICH
        resident engine reloads — a corrupt candidate 409s only its own
        tenant, every neighbor's generation is untouched."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        t = _req_tenant(req)
        try:
            inst = t.deployed.reload_latest()
        except Exception as e:
            # verification refused the candidate (corrupt blob, failed
            # sanity check, no completed instance): 409, old model serves on
            log.error("reload refused (app=%s): %s", t.name, e)
            return json_response(
                409,
                {
                    "message": f"reload refused: {e}",
                    "app": t.name,
                    "engineInstanceId": t.deployed.instance.id,
                },
            )
        return json_response(
            200,
            {"message": "Reloaded", "app": t.name, "engineInstanceId": inst.id},
        )

    @app.route("GET", "/lifecycle\\.json")
    def lifecycle_json(req: Request) -> Response:
        """Generation manifest + canary/controller state — gated like the
        other debug routes."""
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        store = deployed.generation_store
        body: dict[str, Any] = {
            "engineInstanceId": deployed.instance.id,
            "variant": variant_label,
            "manifest": store.snapshot() if store is not None else None,
            "controller": (
                app.lifecycle.snapshot()
                if app.lifecycle is not None
                else {"enabled": False}
            ),
        }
        canary = deployed.canary_instance
        body["canary_in_progress"] = canary is not None
        if canary is not None:
            body["canary_instance"] = canary.id
            body["canary_fraction"] = deployed.canary_split()[1]
        return json_response(200, body)

    # -- plugins (CreateServer.scala:656-702) --------------------------------
    @app.route("GET", "/plugins\\.json")
    def list_plugins(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        return json_response(200, {"plugins": plugins.descriptions()})

    @app.route(
        "GET", "/plugins/(?P<ptype>[^/]+)/(?P<pname>[^/]+)(?P<rest>/.*)?"
    )
    def plugin_rest(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        return plugins.rest_response(
            req.params["ptype"], req.params["pname"],
            req.params.get("rest") or "/", req.query,
        )

    @app.route("POST", "/stop")
    def stop(req: Request) -> Response:
        if not _authorized(req):
            return error_response(401, "Invalid accessKey.")
        if on_stop is not None:
            threading.Thread(target=on_stop, daemon=True).start()
        return json_response(200, {"message": "Shutting down."})

    # profiling now lives at POST /debug/profile (obs/http.py): bounded
    # capture window, off-request-thread stop, key-required arming — the
    # old ungated /profiler/start|stop pair is gone

    return app


def deploy_engine(
    engine_factory_name: str,
    storage: StorageRuntime | None = None,
    engine_instance_id: str | None = None,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
) -> DeployedEngine:
    """Resolve factory + engine instance and materialize models for serving.

    Mirrors CreateServer.createPredictionServerWithEngine:193: given an
    explicit instance id, the generation manifest's **live** generation
    (checksum-verified, with a last-good fallback walk when the head's
    bytes are corrupt), or the latest COMPLETED instance.  Binding the
    manifest's live generation — not merely "latest COMPLETED" — is what
    makes a SIGKILL mid-swap safe: a restart comes back on whichever whole
    generation the atomic manifest commit last published.
    """
    storage = storage or get_storage()
    instances = storage.engine_instances()
    gen_store = GenerationStore(
        storage.models(), engine_id, engine_version, engine_variant
    )
    instance = None
    refused: set[str] = set()
    if engine_instance_id is not None:
        instance = instances.get(engine_instance_id)
        if instance is None:
            raise RuntimeError(f"engine instance {engine_instance_id} not found")
    elif gen_store.exists():
        instance = _bind_from_manifest(gen_store, instances, refused)
    if instance is None:
        instance = instances.get_latest_completed(
            engine_id, engine_version, engine_variant
        )
        if instance is None:
            raise RuntimeError(
                f"no COMPLETED engine instance for engine {engine_id!r}; "
                "run train first"
            )
        if instance.id in refused:
            # every manifest generation failed its checksum AND the latest
            # COMPLETED instance is one of the refused ones: re-recording
            # it live would bless the corruption the gate just caught —
            # refuse to serve garbage, loudly
            raise RuntimeError(
                f"every generation of engine {engine_id!r} failed checksum "
                f"verification (latest COMPLETED {instance.id} included); "
                "re-train or restore the model store before deploying"
            )
    # record what we bound as the live generation (creates the manifest on
    # first deploy — best-effort bookkeeping; verification failures at BIND
    # time for manifest-tracked generations stay strict above)
    try:
        live = gen_store.live()
        if live is None or live.instance_id != instance.id:
            gen_store.record(instance.id, status="live")
    except Exception as e:
        log.warning("could not record live generation in manifest: %s", e)
    factory = resolve_engine_factory(
        engine_factory_name or instance.engine_factory
    )
    engine = factory()
    return DeployedEngine(engine, instance, storage, generation_store=gen_store)


def _bind_from_manifest(
    gen_store: GenerationStore, instances, refused: set[str] | None = None
) -> EngineInstance | None:
    """The startup bind: the manifest's live generation, checksum-verified;
    corrupt bytes fall back to the most recent previously-live generation
    instead of crashing (or serving garbage).  Refused instance ids are
    collected so the caller's latest-COMPLETED fallback never re-blesses
    a generation the checksum gate just rejected."""
    for gen in gen_store.bind_candidates():
        inst = instances.get(gen.instance_id)
        if inst is None:
            continue
        try:
            gen_store.verify(gen)
        except CorruptModelError as e:
            if refused is not None:
                refused.add(gen.instance_id)
            REGISTRY.counter(
                "pio_lifecycle_corrupt_blobs_total",
                "Model blobs refused by checksum verification",
            ).inc()
            log.error(
                "generation %s refused at bind (%s); falling back to "
                "last-good", gen.instance_id, e,
            )
            gen_store.mark_corrupt(gen.instance_id, str(e))
            continue
        return inst
    return None


def undeploy_stale(host: str, port: int, access_key: str | None = None) -> bool:
    """POST /stop to whatever serves on (host, port) before binding — the
    MasterActor's undeploy-then-bind behavior (CreateServer.scala:281-306)."""
    import urllib.request

    probe_host = "127.0.0.1" if host in ("0.0.0.0", "") else host
    url = f"http://{probe_host}:{port}/stop"
    if access_key:
        url += f"?accessKey={access_key}"
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url, method="POST"), timeout=3
        ):
            pass
        time.sleep(0.5)  # give the old server a beat to release the port
        return True
    except Exception:
        return False


def create_prediction_server(
    engine_factory_name: str,
    host: str = "0.0.0.0",
    port: int = 8000,
    storage: StorageRuntime | None = None,
    engine_instance_id: str | None = None,
    engine_id: str = "default",
    engine_version: str = "default",
    engine_variant: str = "default",
    feedback: FeedbackConfig | None = None,
    access_key: str | None = None,
    server_kind: str = "aio",
    registry: MetricsRegistry | None = None,
    max_queue: int | None = None,
    max_inflight: int | None = None,
    default_deadline_s: float | None = None,
    enable_lifecycle: bool | None = None,
):
    """Build the deploy server.

    ``server_kind="aio"`` (default) serves under the asyncio front end with
    query micro-batching — concurrent /queries.json requests coalesce into
    one vectorized predict per wave.  ``"threaded"`` keeps the stdlib
    thread-per-connection server (no batching)."""
    if port:
        if undeploy_stale(host, port, access_key):
            log.info("undeployed stale server on port %d", port)
    deployed = deploy_engine(
        engine_factory_name,
        storage=storage,
        engine_instance_id=engine_instance_id,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
    )
    server_ref: list[Any] = []

    def on_stop():
        if server_ref:
            server_ref[0].shutdown()

    app = create_prediction_server_app(
        deployed,
        feedback=feedback,
        on_stop=on_stop,
        access_key=access_key,
        use_microbatch=server_kind == "aio",
        registry=registry,
        max_queue=max_queue,
        max_inflight=max_inflight,
        default_deadline_s=default_deadline_s,
        enable_lifecycle=enable_lifecycle,
    )
    if server_kind == "aio":
        from predictionio_tpu.server.aio import AsyncAppServer

        server = AsyncAppServer(app, host, port)
    else:
        server = AppServer(app, host, port)
    server_ref.append(server)
    return server


def deploy_tenant_engines(
    specs: list[dict],
    storage: StorageRuntime | None = None,
    hbm_budget_bytes: int | None = None,
    registry: MetricsRegistry | None = None,
) -> TenantRegistry:
    """Deploy SEVERAL engines into one TenantRegistry — the multi-tenant
    replica's boot path (``pio deploy --app name=... --app name=...``).

    Each spec is ``{"app": name, "engine_factory": ..., "engine_id": ...,
    "engine_version": ..., "engine_variant": ..., "engine_instance_id": ...,
    "quota_rps": ..., "quota_burst": ..., "max_inflight": ...,
    "default_deadline_s": ..., "access_key": ...}`` (only ``app`` and
    ``engine_factory`` required).  Admission bin-packs each engine's
    manifest-declared HBM footprint against ``hbm_budget_bytes``: a tenant
    that does not fit raises :class:`TenantAdmissionError` naming the
    shortfall, and already-admitted residents are untouched."""
    tenants = TenantRegistry(
        hbm_budget_bytes=hbm_budget_bytes, registry=registry
    )
    for spec in specs:
        dep = deploy_engine(
            spec.get("engine_factory") or spec.get("engine_factory_name") or "",
            storage=storage,
            engine_instance_id=spec.get("engine_instance_id"),
            engine_id=spec.get("engine_id", "default"),
            engine_version=spec.get("engine_version", "default"),
            engine_variant=spec.get("engine_variant", "default"),
        )
        quota = None
        if spec.get("quota_rps"):
            quota = TokenBucket(
                float(spec["quota_rps"]), spec.get("quota_burst")
            )
        tenants.admit(
            Tenant(
                spec["app"],
                dep,
                quota=quota,
                max_inflight=spec.get("max_inflight"),
                default_deadline_s=spec.get("default_deadline_s"),
                access_key=spec.get("access_key"),
            )
        )
    return tenants


def create_multi_tenant_server_app(
    tenants: TenantRegistry, **kwargs: Any
) -> HTTPApp:
    """A prediction-server app over an ALREADY-POPULATED TenantRegistry:
    the registry's default tenant anchors the legacy single-engine
    surfaces (/, /status.json engineInstanceId), every other surface is
    tenant-resolved per request."""
    default = tenants.default
    if default is None:
        raise ValueError("tenant registry has no resident tenants")
    return create_prediction_server_app(
        default.deployed, tenants=tenants, **kwargs
    )
