"""HTTP servers: event collection, prediction serving, admin, dashboard."""
