"""Asyncio HTTP server front end for an :class:`HTTPApp`.

The serving-latency-critical replacement for the thread-per-connection
``AppServer`` (httpd.py): one event loop multiplexes every connection, async
handlers can await the query :class:`MicroBatcher`, and sync handlers are
pushed to the default executor so storage I/O never blocks the loop.  This is
the akka-http role (workflow/CreateServer.scala:319-324) done the Python
way — stdlib only, HTTP/1.1 with keep-alive.

``HTTPApp`` routes registered with ``route`` work unchanged; handlers that
are coroutine functions (``async def``) are awaited on the loop.  The same
app object therefore serves under both the threaded server (tests, simple
tools) and this one (deploy hot path).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import logging
import os
import ssl as ssl_mod
import threading
import time
from typing import Any
from urllib.parse import parse_qs, urlsplit

from predictionio_tpu.obs.disttrace import (
    TRACE_ID_HEADER,
    adopt_trace_context,
    bind_parent_span,
    reset_parent_span,
)
from predictionio_tpu.obs.flight import begin_annotations, end_annotations
from predictionio_tpu.obs.http import (
    is_observability_path,
    record_request_outcome,
)
from predictionio_tpu.obs.logging import (
    REQUEST_ID_HEADER,
    new_request_id,
    reset_request_context,
    set_request_context,
)
from predictionio_tpu.obs.metrics import REGISTRY
from predictionio_tpu.obs.provenance import (
    begin_capture,
    end_capture,
    wants_deep,
)
from predictionio_tpu.obs.tracing import trace
from predictionio_tpu.resilience.deadline import deadline_scope
from predictionio_tpu.server.httpd import (
    HTTPApp,
    Request,
    Response,
    admission_expired_response,
    admit_request,
    error_response,
    exception_response,
    header_get,
    request_budget,
    unquote_groups,
)

log = logging.getLogger("predictionio_tpu.aio")

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 64 * 1024 * 1024

#: whole-server request timing (handler + executor hop), coarse labels only —
#: per-route latency belongs to the app's own pio_request_latency_seconds
_m_http = REGISTRY.histogram(
    "pio_http_request_seconds",
    "Async front-end request handling time by server/method/status",
    labelnames=("server", "method", "status"),
)

#: label-cardinality guard: the method token is client-controlled (any word
#: parses), so unknown verbs collapse to OTHER instead of minting unbounded
#: histogram children in the process-global registry
_KNOWN_METHODS = frozenset(
    ("GET", "POST", "PUT", "DELETE", "HEAD", "OPTIONS", "PATCH")
)


async def _handle_app_request(app: HTTPApp, req: Request) -> Response:
    """Route like HTTPApp.handle with the request-lifecycle bookkeeping of
    httpd.observe_request, async-shaped: mint/adopt the request id, bind the
    logging context, wrap the handler in an unrecorded root span, echo
    ``X-Pio-Request-Id``, feed SLO + flight.  Observability/probe paths skip
    the span + accounting so scrapes never pollute the SLO window."""
    t0 = time.perf_counter()
    rid = header_get(req.headers, REQUEST_ID_HEADER) or new_request_id()
    if is_observability_path(req.path):
        resp = await _route_app_request(app, req)
    else:
        resp = await _observe_app_request(app, req, rid, t0)
    resp.headers.setdefault(REQUEST_ID_HEADER, rid)
    method = req.method if req.method in _KNOWN_METHODS else "OTHER"
    _m_http.labels(app.name, method, str(resp.status)).observe(
        time.perf_counter() - t0
    )
    return resp


async def _observe_app_request(
    app: HTTPApp, req: Request, rid: str, t0: float
) -> Response:
    """The accounted (non-observability) request path: admission control,
    deadline binding, root span, SLO + flight accounting."""
    adm, shed = admit_request(app, req)
    if shed is not None:
        return shed
    budget = request_budget(app, req)
    # cross-process tracing: adopt the caller's trace id (or start a new
    # trace under this request id) and the span our roots parent under
    tid, parent_span = adopt_trace_context(req.headers, rid)
    tokens = set_request_context(rid, tid)
    ptoken = bind_parent_span(parent_span)
    ann_token = begin_annotations()
    # decision-provenance scope: cheap capture always, deep on X-Pio-Explain
    prov_token = begin_capture(deep=wants_deep(req.headers))
    try:
        if budget is not None and budget <= 0:
            return admission_expired_response(app)
        with deadline_scope(budget_s=budget):
            with trace(f"http.{app.name}", record=False) as span:
                resp = await _route_app_request(app, req)
                span.tags = {
                    "method": req.method,
                    "path": req.path,
                    "status": resp.status,
                }
            try:
                record_request_outcome(
                    app, req, resp, time.perf_counter() - t0, span
                )
            except Exception:  # telemetry must never fail the request
                pass
        resp.headers.setdefault(TRACE_ID_HEADER, tid)
        return resp
    finally:
        if adm is not None:
            adm.release()
        end_capture(prov_token)
        end_annotations(ann_token)
        reset_parent_span(ptoken)
        reset_request_context(tokens)


async def _route_app_request(app: HTTPApp, req: Request) -> Response:
    fn, m, status = app.match(req)
    denied = app.auth_error(req, fn)
    if denied is not None:
        return denied
    if fn is None:
        return error_response(
            status, "Method Not Allowed" if status == 405 else "Not Found"
        )
    req.params = unquote_groups(m)
    try:
        if inspect.iscoroutinefunction(fn):
            return await fn(req)
        loop = asyncio.get_running_loop()
        # copy_context: run_in_executor does not propagate contextvars, and
        # sync handlers must still see the request id / annotation scope
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(None, ctx.run, fn, req)
    except Exception as e:
        return exception_response(e)


async def _read_request(reader: asyncio.StreamReader) -> Request | None:
    """Parse one HTTP/1.1 request; None on clean EOF before a request."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise
    except asyncio.LimitOverrunError:
        raise ValueError("request head too large")
    if len(head) > _MAX_HEADER_BYTES:
        raise ValueError("request head too large")
    lines = head.decode("latin-1").split("\r\n")
    method, target, _version = lines[0].split(" ", 2)
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, _, v = line.partition(":")
        headers[k.strip().lower()] = v.strip()
    length = int(headers.get("content-length") or 0)
    if length > _MAX_BODY_BYTES:
        raise ValueError("request body too large")
    body = await reader.readexactly(length) if length else b""
    if "?" in target:
        split = urlsplit(target)
        q = parse_qs(split.query, keep_blank_values=True)
        path, query = split.path, {k: v[0] for k, v in q.items()}
    else:  # hot path: no query string to parse
        path, query = target, {}
    return Request(
        method=method.upper(),
        path=path,
        query=query,
        headers=headers,
        body=body,
    )


def _encode_response(resp: Response, keep_alive: bool) -> bytes:
    payload, ctype = resp.encoded()
    lines = [
        f"HTTP/1.1 {resp.status} {_reason(resp.status)}",
        f"Content-Type: {ctype}",
        f"Content-Length: {len(payload)}",
        f"Connection: {'keep-alive' if keep_alive else 'close'}",
    ]
    lines += [f"{k}: {v}" for k, v in resp.headers.items()]
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    return head + payload


def _reason(status: int) -> str:
    import http

    try:
        return http.HTTPStatus(status).phrase
    except ValueError:
        return "Unknown"


class AsyncAppServer:
    """Bind an HTTPApp on host:port under an asyncio event loop.

    Mirrors the AppServer surface (start_background / serve_forever /
    shutdown, .host/.port) so callers can swap front ends freely.  TLS comes
    from the same PIO_SSL_CERTFILE/PIO_SSL_KEYFILE env vars.
    """

    def __init__(
        self,
        app: HTTPApp,
        host: str = "0.0.0.0",
        port: int = 8000,
        ssl_certfile: str | None = None,
        ssl_keyfile: str | None = None,
    ):
        self.app = app
        self._req_host = host
        self._req_port = port
        certfile = ssl_certfile or os.environ.get("PIO_SSL_CERTFILE")
        keyfile = ssl_keyfile or os.environ.get("PIO_SSL_KEYFILE")
        self._ssl_ctx = None
        if certfile:
            self._ssl_ctx = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(certfile, keyfile)
        self.host: str = host
        self.port: int = port
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server: asyncio.Server | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()
        self._stopped = threading.Event()

    async def _client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except (ValueError, asyncio.IncompleteReadError) as e:
                    writer.write(
                        _encode_response(
                            error_response(400, f"bad request: {e}"), False
                        )
                    )
                    await writer.drain()
                    return
                if req is None:
                    return
                resp = await _handle_app_request(self.app, req)
                keep = req.headers.get("connection", "keep-alive") != "close"
                writer.write(_encode_response(resp, keep))
                await writer.drain()
                if not keep:
                    return
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._client,
            self._req_host,
            self._req_port,
            ssl=self._ssl_ctx,
        )
        sock = self._server.sockets[0].getsockname()
        self.host, self.port = sock[0], sock[1]
        self._started.set()
        async with self._server:
            try:
                await self._server.serve_forever()
            except asyncio.CancelledError:
                pass

    def _run_loop(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._serve())
        except asyncio.CancelledError:
            pass
        except BaseException as e:  # surface bind/TLS errors to the caller
            self._startup_error = e
            raise
        finally:
            self._started.set()  # unblock start_background on failure too
            try:
                self._loop.run_until_complete(self._loop.shutdown_asyncgens())
            finally:
                self._loop.close()
                self._stopped.set()

    def _start_app_daemons(self) -> None:
        """Per-app daemons (the alert evaluator) start when the app starts
        SERVING, mirroring httpd.AppServer — app construction stays
        thread-free."""
        alerts = getattr(self.app, "alerts", None)
        if alerts is not None and getattr(
            self.app, "alerts_autostart", False
        ):
            alerts.start()

    def start_background(self) -> "AsyncAppServer":
        self._start_app_daemons()
        self._startup_error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run_loop, name=f"{self.app.name}-aio", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("async server failed to start within 10s")
        if self._startup_error is not None:
            raise RuntimeError(
                f"async server failed to start: {self._startup_error}"
            ) from self._startup_error
        return self

    def serve_forever(self) -> None:
        self._start_app_daemons()
        self._run_loop()

    def shutdown(self) -> None:
        loop, server = self._loop, self._server
        if loop is None or server is None:
            return

        def _cancel_all():
            for task in asyncio.all_tasks(loop):
                task.cancel()

        def _stop():
            server.close()  # stop accepting; give in-flight responses
            loop.call_later(0.3, _cancel_all)  # a beat to flush (/stop ack)

        loop.call_soon_threadsafe(_stop)
        # close the micro-batcher BEFORE the loop dies: queued submits get
        # failed while their futures can still be delivered (handlers answer
        # 500 instead of hanging), and the worker thread is released so
        # repeated deploy/shutdown cycles don't accumulate idle executors
        batcher = getattr(self.app, "microbatcher", None)
        if batcher is not None:
            batcher.close()
        alerts = getattr(self.app, "alerts", None)
        if alerts is not None:
            alerts.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)
        else:
            self._stopped.wait(timeout=5)
