"""Query micro-batching: coalesce concurrent in-flight queries into one
device dispatch.

The reference serves each query solo on an akka-http dispatcher thread
(workflow/CreateServer.scala:484-513, with a "TODO: Parallelize" at :507).
On TPU the right shape is the opposite: one batched XLA dispatch per wave of
concurrent queries — a [B, rank] x [rank, n_items] matmul + top-k amortizes
dispatch overhead B-fold and rides the MXU.

``MicroBatcher`` implements *natural batching* (no artificial delay): the
first query dispatches immediately; queries arriving while a dispatch is in
flight queue up and go out together in the next wave, capped at
``max_batch``.  At low load every query is solo (minimum latency); at high
load waves grow to the cap (maximum throughput).  Dispatches run on a single
executor thread, which also serializes device access.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Sequence


class MicroBatcher:
    """Coalesce ``submit``-ed items into batched ``batch_fn`` calls.

    ``batch_fn(items) -> results`` must return one result per item, in
    order.  It runs on a *dedicated* single worker thread (not the loop's
    default executor): sync route handlers doing storage I/O share the
    default pool, and a queue-full default pool would delay dispatch waves
    under mixed load — tail latency, not throughput.
    """

    def __init__(
        self,
        batch_fn: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        self._pending: deque[tuple[Any, asyncio.Future]] = deque()
        self._lock = threading.Lock()
        self._dispatching = False
        self._closed = False
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="microbatch"
        )
        #: wave-size histogram for the status page ({batch_size: count})
        self.wave_sizes: dict[int, int] = {}

    async def submit(self, item: Any) -> Any:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._lock:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._pending.append((item, fut))
            # dispatch under the lock: close() sets _closed under the same
            # lock before shutting the executor down, so a submit that
            # passed the check above cannot hit a dead executor
            if not self._dispatching:
                self._dispatching = True
                loop.run_in_executor(self._executor, self._drain, loop)
        return await fut

    def close(self) -> None:
        """Stop accepting work, fail anything still queued, and wait for the
        in-flight wave — otherwise queued submit() futures would hang until
        client timeout and late submits would hit a dead executor."""
        with self._lock:
            self._closed = True
            dropped = list(self._pending)
            self._pending.clear()
        err = RuntimeError("MicroBatcher closed during shutdown")
        try:
            for _, fut in dropped:
                try:
                    fut.get_loop().call_soon_threadsafe(
                        _fail_if_pending, fut, err
                    )
                except RuntimeError:
                    # the futures' loop is already closed (server tore the
                    # loop down first) — nothing can await them anymore
                    pass
        finally:
            # BOUNDED wait for the in-flight wave: a wedged batch_fn (e.g. a
            # stalled device dispatch) must not hang server shutdown forever;
            # past the deadline the daemon worker thread is abandoned
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._dispatching:
                        break
                time.sleep(0.01)
            self._executor.shutdown(wait=False)

    def _drain(self, loop: asyncio.AbstractEventLoop) -> None:
        """Worker-thread loop: keep dispatching waves until the queue is
        empty, then clear the dispatching flag."""
        while True:
            with self._lock:
                if not self._pending:
                    self._dispatching = False
                    return
                wave = [
                    self._pending.popleft()
                    for _ in range(min(len(self._pending), self.max_batch))
                ]
            items = [it for it, _ in wave]
            try:
                results = self.batch_fn(items)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"batch_fn returned {len(results)} results "
                        f"for {len(items)} items"
                    )
                self.wave_sizes[len(items)] = (
                    self.wave_sizes.get(len(items), 0) + 1
                )
                # ONE loop wakeup per wave (call_soon_threadsafe writes to
                # the loop's self-pipe — per-item calls would cost a syscall
                # + handle each)
                loop.call_soon_threadsafe(
                    _resolve_wave, [f for _, f in wave], results, None
                )
            except Exception as e:
                loop.call_soon_threadsafe(
                    _resolve_wave, [f for _, f in wave], None, e
                )


def _fail_if_pending(fut: asyncio.Future, err: BaseException) -> None:
    if not fut.done():
        fut.set_exception(err)


def _resolve_wave(futures, results, error) -> None:
    if error is not None:
        for fut in futures:
            if not fut.cancelled():
                fut.set_exception(error)
    else:
        for fut, res in zip(futures, results):
            if not fut.cancelled():
                fut.set_result(res)
