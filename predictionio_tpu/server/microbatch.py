"""Query micro-batching: coalesce concurrent in-flight queries into one
device dispatch.

The reference serves each query solo on an akka-http dispatcher thread
(workflow/CreateServer.scala:484-513, with a "TODO: Parallelize" at :507).
On TPU the right shape is the opposite: one batched XLA dispatch per wave of
concurrent queries — a [B, rank] x [rank, n_items] matmul + top-k amortizes
dispatch overhead B-fold and rides the MXU.

``MicroBatcher`` implements *natural batching* (no artificial delay): the
first query dispatches immediately; queries arriving while a dispatch is in
flight queue up and go out together in the next wave, capped at
``max_batch``.  At low load every query is solo (minimum latency); at high
load waves grow to the cap (maximum throughput).  Dispatches run on ONE
long-lived DAEMON worker thread, which also serializes device access —
daemon so a wedged ``batch_fn`` (a stalled device dispatch) can never block
interpreter exit, long-lived so the hot path never pays thread creation.

Resilience semantics (docs/robustness.md):

- the queue is *bounded* (``max_queue``): past the bound, ``submit`` sheds
  with :class:`~predictionio_tpu.resilience.LoadShed` instead of letting
  the backlog grow without limit under overload;
- each item captures the submitter's deadline; items whose deadline passed
  while queued resolve with ``DeadlineExceeded`` *before* the wave
  dispatches — no device time for answers nobody is waiting for — and the
  wave's earliest deadline is re-bound around ``batch_fn`` so outbound
  storage calls inside it stay under budget;
- a ``batch_fn`` exception on a multi-item wave triggers ONE bounded
  solo-retry pass, so a poison query fails alone instead of failing its
  wave-mates.

**Pipelined dispatch** (docs/performance.md): a ``batch_fn`` that returns a
:class:`PendingWave` splits the wave into a *dispatch* half (parse, entity
gather, h2d, async device dispatch — everything up to the fence) and a
*finalize* half (``block_until_ready``/d2h/serialize) that runs on a
dedicated finalizer thread.  The worker is then immediately free to
dispatch wave N+1 while wave N's finalize drains — parse→gather→h2d of the
next wave overlaps compute of the current one, MPMD-pipelining style
(arXiv 2412.14374), bounded by ``max_inflight_waves``.  Results resolve in
wave order (single FIFO finalizer); deadline, solo-retry, and close()
semantics are identical to the synchronous path, and per-item meta carries
the ``dispatch_s``/``finalize_s`` split plus ``pipelined: True`` so the
stage clocks prove exactly what moved off the critical path.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Sequence

from predictionio_tpu.obs import device as device_obs
from predictionio_tpu.obs.contention import ContendedCondition
from predictionio_tpu.obs.disttrace import (
    bind_parent_span,
    current_trace_context,
    reset_parent_span,
)
from predictionio_tpu.obs.logging import (
    get_request_id,
    reset_request_context,
    ring_debug,
    set_request_context,
)
from predictionio_tpu.obs.metrics import (
    REGISTRY,
    MetricsRegistry,
    SIZE_BUCKETS,
)
from predictionio_tpu.resilience import LoadShed, faults
from predictionio_tpu.resilience.admission import shed_counter
from predictionio_tpu.resilience.deadline import (
    DeadlineExceeded,
    deadline_scope,
    get_deadline,
)
from predictionio_tpu.resilience.deadline import _now as _deadline_now

log = logging.getLogger("predictionio_tpu.microbatch")


class PendingWave:
    """A dispatched-but-unfenced wave: ``batch_fn`` returns one of these
    when it has already done the pre-fence work (parse/gather/h2d + async
    JAX dispatch, NO blocking) and defers the fence.  ``finalize()`` runs
    on the MicroBatcher's finalizer thread, blocks until the device results
    land, and returns one result per item in order — the only place the
    pipeline is allowed to synchronize (the serialize fence)."""

    __slots__ = ("finalize",)

    def __init__(self, finalize: Callable[[], Sequence[Any]]):
        self.finalize = finalize


class _InflightWave:
    """One dispatched wave waiting for its finalize fence."""

    __slots__ = (
        "live", "pending", "wave_seq", "loop", "t_dispatch", "wave_t0",
        "dispatch_s", "timeline", "wave_deadline", "depth_at_enqueue",
    )

    def __init__(self, **kw):
        for name, value in kw.items():
            setattr(self, name, value)


class MicroBatcher:
    """Coalesce ``submit``-ed items into batched ``batch_fn`` calls.

    ``batch_fn(items) -> results`` must return one result per item, in
    order.  It runs on a *dedicated* single worker thread (not the loop's
    default executor): sync route handlers doing storage I/O share the
    default pool, and a queue-full default pool would delay dispatch waves
    under mixed load — tail latency, not throughput.

    Per-wave telemetry lands in ``registry`` (default: the process
    registry): queue depth, batch size, and the queue-wait vs device-time
    split that decomposes a query's latency into "waiting behind the
    in-flight wave" vs "inside batch_fn on the device".
    """

    def __init__(
        self,
        batch_fn: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        drain_timeout_s: float = 5.0,
        registry: MetricsRegistry | None = None,
        max_queue: int | None = 1024,
        solo_retry: bool = True,
        max_inflight_waves: int = 2,
    ):
        self.batch_fn = batch_fn
        self.max_batch = max_batch
        #: pipelined waves allowed between dispatch and the finalize fence;
        #: 0 finalizes inline on the worker (pipelining off — the pre-PR-13
        #: serial behavior, useful for tests and debugging)
        self.max_inflight_waves = max(int(max_inflight_waves), 0)
        #: how long close() waits for the in-flight wave before abandoning
        #: the daemon worker (was a hard-coded 5.0 s deadline)
        self.drain_timeout_s = drain_timeout_s
        #: queued (not in-flight) items past which submit() sheds with
        #: LoadShed -> 503 + Retry-After; None = unbounded (legacy)
        self.max_queue = max_queue
        #: retry a failed multi-item wave one item at a time so a poison
        #: query fails alone (one bounded pass, never recursive)
        self.solo_retry = solo_retry
        #: (item, future, enqueue_time, request_id, meta, deadline,
        #:  (trace_id, parent_span_id))
        self._pending: deque[
            tuple[
                Any, asyncio.Future, float, str | None, dict | None,
                float | None, tuple,
            ]
        ] = deque()
        #: every submitter and the worker serialize on this condition: when
        #: wave coalescing degrades under concurrency, this is the first
        #: lock to suspect — so its blocked acquisitions are metered
        #: (pio_lock_wait_seconds{lock="microbatch"}, obs/contention.py)
        self._cond = ContendedCondition("microbatch", registry=registry)
        self._worker: threading.Thread | None = None
        self._in_wave = False
        self._closed = False
        #: dispatched waves waiting for their finalize fence (FIFO: results
        #: resolve in wave order) + the finalizer's busy flag — close() and
        #: ``busy`` treat an unfenced wave exactly like an in-flight one
        self._inflight: deque[_InflightWave] = deque()
        self._finalizing = False
        self._finalizer: threading.Thread | None = None
        #: wave-size histogram for the status page ({batch_size: count})
        self.wave_sizes: dict[int, int] = {}
        #: rolling window of recent wave sizes feeding the coalescing-rate
        #: gauge (items per wave) — the effect-size twin of the lock-wait
        #: metrics: contention on the submit path shows up here as waves
        #: shrinking toward 1
        self._recent_waves: deque[int] = deque(maxlen=64)
        #: monotonically increasing wave number, exposed through per-item
        #: meta so downstream consumers (flight recorder, prediction log)
        #: can tell which dispatch wave served a request
        self._wave_seq = 0
        #: label for the batch_fn fault-injection seam
        self._fault_label = getattr(
            batch_fn, "__qualname__", getattr(batch_fn, "__name__", "batch_fn")
        )
        reg = registry or REGISTRY
        self._m_queue_depth = reg.gauge(
            "pio_microbatch_queue_depth",
            "Queries queued behind the in-flight wave",
        )
        self._m_batch_size = reg.histogram(
            "pio_microbatch_batch_size",
            "Queries coalesced per dispatch wave",
            buckets=SIZE_BUCKETS,
        )
        self._m_queue_wait = reg.histogram(
            "pio_microbatch_queue_wait_seconds",
            "Per-query wait from submit to wave dispatch",
        )
        self._m_device_time = reg.histogram(
            "pio_microbatch_device_seconds",
            "Per-wave batch_fn (device dispatch) duration",
        )
        #: the 4-way split of device_s (host_gather/h2d/compute/d2h, plus
        #: the unattributed remainder as "other"), labeled by the device
        #: the engine marked — the per-shard extension point for sharded
        #: serving (ROADMAP item 1)
        self._m_stage_time = reg.histogram(
            "pio_microbatch_stage_seconds",
            "Per-wave duration split by timeline stage and device",
            labelnames=("stage", "device"),
            buckets=device_obs.WAVE_STAGE_BUCKETS,
        )
        self._m_drain_timeout = reg.counter(
            "pio_microbatch_drain_timeout_total",
            "close() deadlines expired with a wave still in flight",
        )
        self._m_shed = shed_counter(reg).labels("queue")
        self._m_expired = reg.counter(
            "pio_microbatch_deadline_expired_total",
            "Queued queries resolved with a deadline error before dispatch",
        )
        self._m_solo_retry = reg.counter(
            "pio_microbatch_solo_retry_total",
            "Failed waves retried item-by-item to isolate a poison query",
        )
        self._m_coalescing = reg.gauge(
            "pio_microbatch_coalescing_rate",
            "Queries coalesced per dispatch wave over a rolling window",
        )

    def wave_histogram(self) -> dict[int, int]:
        """Consistent snapshot of the wave-size histogram.

        Prefer this over reading ``wave_sizes`` directly while traffic is
        in flight: the worker mutates the dict under ``_cond``, and an
        unlocked concurrent iteration can raise ``RuntimeError: dictionary
        changed size during iteration``.
        """
        with self._cond:
            return dict(self.wave_sizes)

    @property
    def draining(self) -> bool:
        """True once close() began — the readiness signal for /readyz."""
        return self._closed

    @property
    def busy(self) -> bool:
        """True while queries are queued, a wave is mid-dispatch, or a
        pipelined wave awaits its finalize fence — the queue-side half of
        the fleet drain check (the generation-refcount half lives on
        DeployedEngine.inflight_snapshot)."""
        with self._cond:
            return (
                bool(self._pending)
                or self._in_wave
                or bool(self._inflight)
                or self._finalizing
            )

    async def submit(self, item: Any, meta: dict | None = None) -> Any:
        """Queue ``item`` for the next wave.  ``meta``, when given, is
        filled by the worker with this item's queue_wait_s / device_s /
        wave_size / wave_request_ids before the result future resolves —
        the per-request latency decomposition for the flight recorder.

        Sheds with :class:`LoadShed` when ``max_queue`` items are already
        queued, and captures the caller's deadline (if one is bound) so the
        worker can expire it instead of dispatching it late."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._cond:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            if (
                self.max_queue is not None
                and len(self._pending) >= self.max_queue
            ):
                self._m_shed.inc()
                raise LoadShed(
                    f"microbatch queue full ({self.max_queue} queued)",
                    retry_after_s=1.0,
                )
            self._pending.append(
                (
                    item,
                    fut,
                    time.perf_counter(),
                    get_request_id(),
                    meta,
                    get_deadline(),
                    # submitter's trace context (trace id + innermost open
                    # span), re-bound around batch_fn so a wave's outbound
                    # storage calls join the request's cross-process trace
                    current_trace_context(),
                )
            )
            self._m_queue_depth.set(len(self._pending))
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, name="microbatch", daemon=True
                )
                self._worker.start()
            # notify_all, not notify: the worker AND the pipeline finalizer
            # sleep on this condition — a single notify could wake only the
            # finalizer (which has nothing to do) and strand the new item
            self._cond.notify_all()
        return await fut

    def close(self) -> None:
        """Stop accepting work, fail anything still queued, and wait
        BOUNDEDLY for the in-flight wave — queued submit() futures must not
        hang until client timeout, and a wedged batch_fn (e.g. a stalled
        device dispatch) must not hang shutdown: past the deadline the
        daemon worker is simply abandoned.  Items whose deadline already
        passed resolve with DeadlineExceeded (not leaked, not mislabeled as
        a shutdown artifact); the rest get the shutdown error."""
        with self._cond:
            self._closed = True
            dropped = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        err = RuntimeError("MicroBatcher closed during shutdown")
        now = _deadline_now()
        for _, fut, _t, _rid, _meta, dl, _tc in dropped:
            item_err: BaseException = err
            if dl is not None and dl <= now:
                self._m_expired.inc()
                item_err = DeadlineExceeded(
                    "query deadline expired while queued (server shutdown)"
                )
            try:
                fut.get_loop().call_soon_threadsafe(
                    _fail_if_pending, fut, item_err
                )
            except RuntimeError:
                # the futures' loop is already closed (server tore the
                # loop down first) — nothing can await them anymore
                pass
        # sleep on the condition until the worker clears _in_wave AND the
        # pipeline drains (the finalizer notifies after every fence) instead
        # of polling: wakeup is immediate and no CPU burns while a long
        # device dispatch drains
        with self._cond:
            if not self._cond.wait_for(
                lambda: not self._in_wave
                and not self._inflight
                and not self._finalizing,
                timeout=self.drain_timeout_s,
            ):
                self._m_drain_timeout.inc()

    def _drain(self) -> None:
        """Persistent worker loop: sleep on the condition until work (or
        close), then dispatch waves."""
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                wave = [
                    self._pending.popleft()
                    for _ in range(min(len(self._pending), self.max_batch))
                ]
                self._in_wave = True
                self._wave_seq += 1
                wave_seq = self._wave_seq
                self._m_queue_depth.set(len(self._pending))
            try:
                self._dispatch_wave(wave, wave_seq)
            finally:
                with self._cond:
                    self._in_wave = False
                    self._cond.notify_all()  # wake close() waiters

    def _call_batch_fn(self, items: list[Any]):
        """The batch_fn fault-injection seam (docs/robustness.md); one
        attribute check when no plan is installed.  May return either the
        results or a :class:`PendingWave` (pipelined dispatch)."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.check("batch_fn", self._fault_label)
        return self.batch_fn(items)

    def _validated(self, results, items: list[Any]) -> Sequence[Any]:
        if len(results) != len(items):
            raise RuntimeError(
                f"batch_fn returned {len(results)} results "
                f"for {len(items)} items"
            )
        return results

    def _run_batch_sync(self, items: list[Any]) -> Sequence[Any]:
        """Dispatch + finalize inline — the solo-retry path (and any other
        caller that needs the whole wave on one thread)."""
        results = self._call_batch_fn(items)
        if isinstance(results, PendingWave):
            results = results.finalize()
        return self._validated(results, items)

    def _fail_or_retry(
        self, live: list[tuple], e: BaseException, wave_seq: int, loop
    ) -> None:
        if len(live) == 1 or not self.solo_retry:
            self._post(loop, [f for _, f, *_ in live], None, e)
        else:
            self._solo_retry_pass(live, e, wave_seq)

    def _dispatch_wave(self, wave: list[tuple], wave_seq: int) -> None:
        t_dispatch = time.perf_counter()
        # deadline re-check at dispatch: items that expired while queued
        # resolve with DeadlineExceeded instead of spending device time on
        # an answer nobody is waiting for
        now = _deadline_now()
        live: list[tuple] = []
        for entry in wave:
            _, fut, t_enq, _, meta, dl, _tc = entry
            if dl is not None and dl <= now:
                self._m_expired.inc()
                if meta is not None:
                    meta["queue_wait_s"] = round(t_dispatch - t_enq, 6)
                    meta["deadline_expired"] = True
                _post_one(
                    fut,
                    error=DeadlineExceeded(
                        "query deadline expired while queued behind the "
                        "in-flight wave"
                    ),
                )
            else:
                live.append(entry)
        if not live:
            return
        items = [it for it, _, _, _, _, _, _ in live]
        futures = [f for _, f, _, _, _, _, _ in live]
        rids = [r for _, _, _, r, _, _, _ in live if r]
        deadlines = [dl for _, _, _, _, _, dl, _ in live if dl is not None]
        wave_deadline = min(deadlines) if deadlines else None
        self._m_batch_size.observe(len(items))
        for _, _, t_enq, _, _, _, _ in live:
            self._m_queue_wait.observe(t_dispatch - t_enq)
        # the correlation line: a wave's log entry names the requests it
        # coalesced, so one slow query's request_id finds its wave
        # mates.  ring_debug reaches /logs.json even when the embedding
        # app never configured logging.
        ring_debug(
            log,
            "microbatch wave dispatched",
            wave_size=len(items),
            wave_seq=wave_seq,
            request_ids=rids,
        )
        # all futures in a wave come from submit() calls on the same
        # server loop; resolve with ONE loop wakeup
        loop = futures[0].get_loop()
        wave_t0 = time.time()
        try:
            # re-bind the wave's tightest deadline around batch_fn so
            # outbound storage calls inside it stay under budget; the wave
            # timeline scope collects the engine's host_gather/h2d/compute/
            # d2h stage marks so device_s stops being one opaque number.
            # The FIRST member's request/trace context is re-bound too, so
            # outbound storage calls inside batch_fn carry that request's
            # trace id across the process boundary (wave-mates' traces
            # still get the device events through their own meta)
            with device_obs.wave_timeline() as timeline:
                with deadline_scope(absolute=wave_deadline):
                    with _wave_context(live[0]):
                        results = self._call_batch_fn(items)
        except Exception as e:
            self._fail_or_retry(live, e, wave_seq, loop)
            return
        if isinstance(results, PendingWave):
            # pipelined wave: the fence moves to the finalizer thread and
            # THIS thread is immediately free to dispatch the next wave —
            # the parse→gather→h2d / compute / d2h-serialize overlap
            job = _InflightWave(
                live=live,
                pending=results,
                wave_seq=wave_seq,
                loop=loop,
                t_dispatch=t_dispatch,
                wave_t0=wave_t0,
                dispatch_s=time.perf_counter() - t_dispatch,
                timeline=timeline,
                wave_deadline=wave_deadline,
                depth_at_enqueue=0,
            )
            if self.max_inflight_waves > 0:
                self._enqueue_inflight(job)
            else:
                self._finalize_wave(job)
            return
        try:
            results = self._validated(results, items)
        except Exception as e:
            self._fail_or_retry(live, e, wave_seq, loop)
            return
        device_s = time.perf_counter() - t_dispatch
        self._m_device_time.observe(device_s)
        breakdown = self._observe_timeline(timeline, device_s)
        self._fill_meta(
            live, t_dispatch, device_s, breakdown, timeline, wave_t0,
            wave_seq, rids,
        )
        self._note_wave(len(items))
        self._post(loop, futures, results, None)

    def _fill_meta(
        self,
        live: list[tuple],
        t_dispatch: float,
        device_s: float,
        breakdown: dict[str, float],
        timeline: "device_obs.WaveTimeline",
        wave_t0: float,
        wave_seq: int,
        rids: list[str],
        extra: dict | None = None,
    ) -> None:
        """Fill per-item timing meta BEFORE resolving the futures:
        call_soon_threadsafe orders these writes before the submitter's
        read on the loop thread."""
        for _, _, t_enq, _, meta, _, _ in live:
            if meta is not None:
                meta["queue_wait_s"] = round(t_dispatch - t_enq, 6)
                meta["device_s"] = round(device_s, 6)
                meta["device_breakdown"] = breakdown
                meta["wave_device"] = timeline.device
                #: wall-clock dispatch time — the distributed timeline's
                #: anchor for the wave's device-track events
                meta["wave_t0"] = round(wave_t0, 6)
                if timeline.fn:
                    meta["wave_fn"] = timeline.fn
                    meta["wave_flops"] = timeline.flops
                    meta["wave_bytes"] = timeline.bytes
                if timeline.shards:
                    # sharded wave: which devices held which bytes
                    meta["wave_shards"] = timeline.shards
                if timeline.shard_seconds:
                    # ... and each device's own settle clock
                    meta["wave_shard_seconds"] = timeline.shard_seconds
                if timeline.cache_hits:
                    # factor-cache hits in this wave: a repeat entity whose
                    # gather was skipped (flight entries prove gather ~ 0)
                    meta["cache_hits"] = timeline.cache_hits
                if timeline.cache_misses:
                    # ... and the misses with their fetch bytes — the cost
                    # ledger's hit-vs-miss billing split (obs/costs.py)
                    meta["cache_misses"] = timeline.cache_misses
                    if timeline.cache_miss_bytes:
                        meta["cache_miss_bytes"] = round(
                            timeline.cache_miss_bytes, 1
                        )
                if timeline.storage_bytes:
                    # event-store bytes the wave's handler read (history
                    # gathers): prorated to members by the cost ledger
                    meta["wave_storage_bytes"] = round(
                        timeline.storage_bytes, 1
                    )
                meta["wave_size"] = len(live)
                meta["wave_seq"] = wave_seq
                #: process-unique wave handle (dispatch wall-ms + seq):
                #: provenance records cite it so "which wave answered this
                #: request" survives across restarts, unlike bare wave_seq
                meta["wave_id"] = f"{int(wave_t0 * 1000):x}-{wave_seq}"
                meta["wave_request_ids"] = rids
                if extra:
                    meta.update(extra)

    # -- pipelined finalize ---------------------------------------------------

    def _enqueue_inflight(self, job: _InflightWave) -> None:
        """Hand a dispatched wave to the finalizer, blocking while the
        in-flight depth is at the bound (bounded pipelining: the worker
        must not run unboundedly ahead of the fence)."""
        with self._cond:
            while (
                len(self._inflight) >= self.max_inflight_waves
                and not self._closed
            ):
                self._cond.wait()
            if self._closed:
                # close() raced this dispatch: an idle finalizer may have
                # already seen (closed, empty) and exited — enqueueing now
                # would strand the wave's futures forever.  Finalize
                # inline instead: close() is still waiting on _in_wave.
                closed = True
            else:
                closed = False
                job.depth_at_enqueue = len(self._inflight) + 1
                self._inflight.append(job)
                if self._finalizer is None or not self._finalizer.is_alive():
                    self._finalizer = threading.Thread(
                        target=self._finalize_loop,
                        name="microbatch-finalize",
                        daemon=True,
                    )
                    self._finalizer.start()
                self._cond.notify_all()
        if closed:
            self._finalize_wave(job)

    def _finalize_loop(self) -> None:
        """FIFO fence runner: results resolve in wave order, one wave's
        finalize at a time, overlapping the worker's next dispatch."""
        while True:
            with self._cond:
                while not self._inflight and not self._closed:
                    self._cond.wait()
                if not self._inflight:
                    return  # closed and drained
                job = self._inflight.popleft()
                self._finalizing = True
                self._cond.notify_all()  # wake a worker blocked on depth
            try:
                self._finalize_wave(job)
            finally:
                with self._cond:
                    self._finalizing = False
                    self._cond.notify_all()  # wake close() waiters

    def _finalize_wave(self, job: _InflightWave) -> None:
        live = job.live
        items = [it for it, _, _, _, _, _, _ in live]
        futures = [f for _, f, _, _, _, _, _ in live]
        rids = [r for _, _, _, r, _, _, _ in live if r]
        # deadline re-check at the fence: an item whose budget ran out while
        # its wave sat in the in-flight pipeline (behind a slow finalize)
        # must still answer an honest 504, exactly like expiry in the
        # dispatch queue — the device work is sunk, the lie is not.  The
        # finalize itself still runs (it releases serving slots).
        now = _deadline_now()
        expired: set[int] = set()
        for j, (_, _, _, _, meta, dl, _tc) in enumerate(live):
            if dl is not None and dl <= now:
                self._m_expired.inc()
                if meta is not None:
                    meta["deadline_expired"] = True
                expired.add(j)
        t_fin = time.perf_counter()
        try:
            with device_obs.wave_timeline() as ftl:
                with deadline_scope(absolute=job.wave_deadline):
                    with _wave_context(live[0]):
                        results = self._validated(
                            job.pending.finalize(), items
                        )
        except Exception as e:
            self._fail_or_retry(live, e, job.wave_seq, job.loop)
            return
        if expired:
            for j in sorted(expired, reverse=True):
                _post_one(
                    live[j][1],
                    error=DeadlineExceeded(
                        "query deadline expired while pipelined behind "
                        "the in-flight wave"
                    ),
                )
            live = [e for j, e in enumerate(live) if j not in expired]
            results = [r for j, r in enumerate(results) if j not in expired]
            futures = [f for _, f, _, _, _, _, _ in live]
            if not live:
                return
        finalize_s = time.perf_counter() - t_fin
        device_s = job.dispatch_s + finalize_s
        self._m_device_time.observe(device_s)
        # merge the dispatch-phase stage marks into the finalize timeline:
        # one breakdown covering both halves (host_gather/h2d from
        # dispatch, compute/d2h from the fence)
        dtl = job.timeline
        for stage, seconds in dtl.stages.items():
            ftl.stages[stage] = ftl.stages.get(stage, 0.0) + seconds
        if ftl.fn is None:
            ftl.fn, ftl.flops, ftl.bytes = dtl.fn, dtl.flops, dtl.bytes
        if ftl.device == "host" and dtl.device != "host":
            ftl.device = dtl.device
        ftl.cache_hits += dtl.cache_hits
        ftl.cache_misses += dtl.cache_misses
        ftl.cache_miss_bytes += dtl.cache_miss_bytes
        ftl.storage_bytes += dtl.storage_bytes
        if not ftl.shards:
            ftl.shards = dtl.shards
        if not ftl.shard_seconds:
            ftl.shard_seconds = dtl.shard_seconds
        breakdown = self._observe_timeline(ftl, device_s)
        self._fill_meta(
            live, job.t_dispatch, device_s, breakdown, ftl, job.wave_t0,
            job.wave_seq, rids,
            extra={
                "pipelined": True,
                "dispatch_s": round(job.dispatch_s, 6),
                "finalize_s": round(finalize_s, 6),
                "inflight_depth": job.depth_at_enqueue,
            },
        )
        self._note_wave(len(items))
        self._post(job.loop, futures, results, None)

    def _note_wave(self, size: int) -> None:
        """Record one dispatched wave's size — under the cond (the status
        page reads ``wave_sizes`` from other threads, and dict writes must
        not race its snapshot) — and refresh the rolling coalescing-rate
        gauge."""
        with self._cond:
            self.wave_sizes[size] = self.wave_sizes.get(size, 0) + 1
            self._recent_waves.append(size)
            self._m_coalescing.set(
                sum(self._recent_waves) / len(self._recent_waves)
            )

    def _observe_timeline(
        self, timeline: "device_obs.WaveTimeline", device_s: float
    ) -> dict[str, float]:
        """Turn the engine's stage marks into the 4-way (+other) breakdown
        that sums to ``device_s`` and record the per-stage histograms,
        labeled by the device the engine marked (the achieved-vs-peak
        gauges are the engine's own responsibility — it observes into the
        efficiency tracker with its compute-stage timing, which is also
        correct when batch_predict runs outside the MicroBatcher)."""
        breakdown = device_obs.split_breakdown(timeline, device_s)
        for stage, seconds in breakdown.items():
            if seconds > 0.0 or stage == "other":
                self._m_stage_time.labels(stage, timeline.device).observe(
                    seconds
                )
        return breakdown

    def _solo_retry_pass(
        self, live: list[tuple], wave_error: BaseException, wave_seq: int
    ) -> None:
        """ONE bounded re-dispatch of a failed wave, item by item, so a
        poison query fails alone instead of failing its wave-mates.  Runs
        inside the same _in_wave window (close() waits for it, boundedly);
        a close() arriving mid-pass fails the remaining items immediately
        with the wave error instead of holding shutdown hostage."""
        self._m_solo_retry.inc()
        log.warning(
            "wave %d (%d items) failed (%s: %s); solo-retrying to isolate",
            wave_seq,
            len(live),
            type(wave_error).__name__,
            wave_error,
        )
        now = _deadline_now()
        for entry in live:
            item, fut, t_enq, _rid, meta, dl, _tc = entry
            if self._closed:
                _post_one(fut, error=wave_error)
                continue
            if dl is not None and dl <= now:
                self._m_expired.inc()
                if meta is not None:
                    meta["deadline_expired"] = True
                _post_one(
                    fut,
                    error=DeadlineExceeded(
                        "query deadline expired during wave retry"
                    ),
                )
                continue
            t0 = time.perf_counter()
            t0_wall = time.time()
            try:
                with device_obs.wave_timeline() as timeline:
                    with deadline_scope(absolute=dl):
                        with _wave_context(entry):
                            # dispatch + finalize inline: a retried item
                            # never re-enters the pipeline
                            result = self._run_batch_sync([item])[0]
            except Exception as e:
                _post_one(fut, error=e)
                continue
            solo_s = time.perf_counter() - t0
            breakdown = self._observe_timeline(timeline, solo_s)
            if meta is not None:
                meta["queue_wait_s"] = round(t0 - t_enq, 6)
                meta["device_s"] = round(solo_s, 6)
                meta["device_breakdown"] = breakdown
                meta["wave_device"] = timeline.device
                meta["wave_t0"] = round(t0_wall, 6)
                if timeline.fn:
                    meta["wave_fn"] = timeline.fn
                    meta["wave_flops"] = timeline.flops
                    meta["wave_bytes"] = timeline.bytes
                if timeline.shards:
                    meta["wave_shards"] = timeline.shards
                if timeline.shard_seconds:
                    meta["wave_shard_seconds"] = timeline.shard_seconds
                if timeline.cache_hits:
                    meta["cache_hits"] = timeline.cache_hits
                if timeline.cache_misses:
                    meta["cache_misses"] = timeline.cache_misses
                    if timeline.cache_miss_bytes:
                        meta["cache_miss_bytes"] = round(
                            timeline.cache_miss_bytes, 1
                        )
                if timeline.storage_bytes:
                    meta["wave_storage_bytes"] = round(
                        timeline.storage_bytes, 1
                    )
                meta["wave_size"] = 1
                meta["wave_seq"] = wave_seq
                meta["solo_retry"] = True
            self._note_wave(1)
            _post_one(fut, result=result)
            now = _deadline_now()

    @staticmethod
    def _post(loop, futures, results, error) -> None:
        try:
            loop.call_soon_threadsafe(_resolve_wave, futures, results, error)
        except RuntimeError:
            pass  # loop already closed during shutdown


@contextlib.contextmanager
def _wave_context(entry: tuple):
    """Re-bind one wave member's request + trace context around a dispatch
    on the worker thread, so outbound calls inside ``batch_fn`` (storage
    daemon round trips) propagate that request's ids across the process
    boundary.  No-op for submitters that carried no context."""
    _, _, _, rid, _, _, (tid, sid) = entry
    if not rid and not tid:
        yield
        return
    tokens = set_request_context(rid, tid)
    ptoken = bind_parent_span(sid)
    try:
        yield
    finally:
        reset_parent_span(ptoken)
        reset_request_context(tokens)


def _post_one(fut: asyncio.Future, result=None, error=None) -> None:
    """Resolve one future from the worker thread (loop-safe)."""
    try:
        fut.get_loop().call_soon_threadsafe(_resolve_one, fut, result, error)
    except RuntimeError:
        pass  # loop already closed during shutdown


def _resolve_one(fut: asyncio.Future, result, error) -> None:
    if fut.done():
        return
    if error is not None:
        fut.set_exception(error)
    else:
        fut.set_result(result)


def _fail_if_pending(fut: asyncio.Future, err: BaseException) -> None:
    if not fut.done():
        fut.set_exception(err)


def _resolve_wave(futures, results, error) -> None:
    if error is not None:
        for fut in futures:
            if not fut.cancelled():
                fut.set_exception(error)
    else:
        for fut, res in zip(futures, results):
            if not fut.cancelled():
                fut.set_result(res)
