"""Event collection REST server (:7070).

Route parity with data/api/EventServer.scala:

  GET  /                       liveness {"status": "alive"}
  POST /events.json            insert one event -> 201 {"eventId"}
  GET  /events.json            query (startTime/untilTime/entityType/entityId/
                               event/targetEntityType/targetEntityId/limit/
                               reversed; default limit 20)
  GET  /events/<id>.json       fetch by id
  DELETE /events/<id>.json     delete by id
  POST /batch/events.json      <=50 events, per-item status list
  GET  /stats.json             hourly counters (requires --stats)
  POST/GET /webhooks/<w>.json  JSON webhook connectors (segmentio)
  POST/GET /webhooks/<w>.form  form webhook connectors (mailchimp)

Auth mirrors EventServer.scala:92-130: ``accessKey`` query param (with
optional ``channel`` name) or HTTP Basic Authorization whose username is the
key.  An access key with a non-empty ``events`` list only accepts those event
names (403 otherwise).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass
from typing import Any

from predictionio_tpu.data.event import Event, EventValidationError
from predictionio_tpu.data.storage.base import EventFilter
from predictionio_tpu.data.storage.config import StorageRuntime, get_storage
from predictionio_tpu.data.storage.remote_backend import RemoteStorageError
from predictionio_tpu.data.webhooks import (
    ConnectorException,
    form_connectors,
    json_connectors,
    to_event,
)
from predictionio_tpu.data.datamap import parse_event_time
from predictionio_tpu.obs.costs import (
    CostLedger,
    default_ledger,
    request_cost,
)
from predictionio_tpu.obs.http import add_observability_routes
from predictionio_tpu.obs.logging import get_request_id
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.quality import QualityMonitor, default_quality
from predictionio_tpu.resilience import faults
from predictionio_tpu.server.httpd import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    error_response,
    json_response,
)
from predictionio_tpu.server.stats import HourlyStats


@dataclass
class AuthData:
    """Resolved access key (EventServer.scala AuthData)."""

    app_id: int
    channel_id: int | None
    events: tuple[str, ...]


class AuthError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


#: event-store failures that mean "temporarily unavailable, retry later" —
#: an unreachable storage daemon (or its open circuit breaker) must answer
#: ingest with 503 + Retry-After, not a 500 traceback, so well-behaved SDK
#: clients back off and retry instead of dropping events
_STORE_UNAVAILABLE = (RemoteStorageError, ConnectionError, TimeoutError)


def _unavailable_response(e: Exception) -> "Response":
    from predictionio_tpu.server.httpd import shed_response

    return shed_response(
        f"event store unavailable: {e}",
        getattr(e, "retry_after_s", 1.0),
    )


def _authenticate(storage: StorageRuntime, req: Request) -> AuthData:
    key = req.query.get("accessKey")
    if key is None:
        header = req.headers.get("Authorization", "")
        if header.startswith("Basic "):
            try:
                decoded = base64.b64decode(header[len("Basic "):]).decode()
            except Exception:
                raise AuthError(401, "Invalid accessKey.") from None
            key = decoded.strip().split(":")[0]
        else:
            raise AuthError(401, "Missing accessKey.")
    k = storage.access_keys().get(key)
    if k is None:
        raise AuthError(401, "Invalid accessKey.")
    channel_id = None
    channel = req.query.get("channel")
    if channel is not None:
        by_name = {
            c.name: c.id for c in storage.channels().get_by_appid(k.appid)
        }
        if channel not in by_name:
            raise AuthError(401, f"Invalid channel '{channel}'.")
        channel_id = by_name[channel]
    return AuthData(app_id=k.appid, channel_id=channel_id, events=tuple(k.events))


def create_event_server_app(
    storage: StorageRuntime | None = None,
    stats: bool = False,
    plugins: "PluginContext | None" = None,
    registry: MetricsRegistry | None = None,
    obs_access_key: str | None = None,
    quality: QualityMonitor | None = None,
    max_write_inflight: int | None = None,
    #: per-app cost ledger (docs/observability.md#cost-attribution): None =
    #: the process default on the default registry, so a single-VM deploy
    #: bills ingest and serving into one rollup
    costs: "CostLedger | None" = None,
) -> HTTPApp:
    import os

    from predictionio_tpu.resilience.admission import AdmissionController
    from predictionio_tpu.server.plugins import PluginContext

    storage = storage or get_storage()
    app = HTTPApp("eventserver")
    hourly = HourlyStats() if stats else None
    levents = storage.l_events()
    plugins = plugins or PluginContext.from_env()
    registry = registry or REGISTRY
    # the cost ledger bills ingest by access-key app id; id-bearing paths
    # collapse so ledger keys stay low-cardinality
    if costs is None:
        costs = (
            default_ledger()
            if registry is REGISTRY
            else CostLedger(registry=registry)
        )
    app.costs = costs

    def _cost_route(path: str) -> str:
        path = path.split("?", 1)[0]
        if path.startswith("/events/"):
            return "/events/*.json"
        if path.startswith("/webhooks/"):
            return "/webhooks/*"
        return path
    # Ingest backpressure: bound the event-store writes in flight so a
    # slow/degraded store sheds 503 + Retry-After BEFORE the write
    # amplifies into a pile of blocked handler threads (docs/data_plane.md).
    # Counted as pio_shed_total{reason="eventstore"}; the default alert
    # pack's ingest_shed rule pages on a sustained shed rate.
    if max_write_inflight is None:
        try:
            max_write_inflight = int(os.environ.get("PIO_EVENT_MAX_INFLIGHT", 256))
        except ValueError:
            max_write_inflight = 256
    ingest_gate = (
        AdmissionController(
            max_write_inflight, registry=registry, reason="eventstore"
        )
        if max_write_inflight and max_write_inflight > 0
        else None
    )

    def gated_write(handler):
        """503 + Retry-After when the write queue is saturated — applied
        to every path that writes the event store."""

        def wrapped(req: Request) -> Response:
            from predictionio_tpu.server.httpd import shed_response

            if ingest_gate is None:
                return handler(req)
            if not ingest_gate.try_acquire():
                # shed before auth: no app identity yet, so the ledger
                # carries it under the shared "unknown" row
                costs.note_shed("unknown", _cost_route(req.path), "ingest")
                return shed_response(
                    "event-store write queue saturated; retry later",
                    ingest_gate.retry_after_s,
                )
            try:
                return handler(req)
            finally:
                ingest_gate.release()

        return wrapped
    # the feedback-joiner half of online model quality: ingested feedback
    # events join back to the prediction log this monitor holds.  Default
    # to the process-global monitor so a single-VM deployment (prediction +
    # event server in one process) closes the loop with zero configuration.
    if quality is None:
        quality = (
            default_quality()
            if registry is REGISTRY
            else QualityMonitor(registry=registry)
        )

    def _event_store_ready() -> bool:
        # live probe, not a captured handle: run_readiness treats a raise
        # as not-ready, so a backend that dies after startup flips /readyz
        return storage.l_events() is not None

    def _metadata_ready() -> bool:
        storage.access_keys().get("__readyz_probe__")
        return True

    # Without an operator key, only the scrape surface (/metrics,
    # /traces.json, health) is exposed, unauthenticated like GET / —
    # scrapers and load balancers carry no per-app access keys, and the
    # registry holds no event payloads.  The DEBUG surface (/logs.json,
    # /debug/flight.json, /debug/profile) leaks log lines / error bodies
    # and arms the profiler, so on this anonymous-facing ingest port it
    # only exists behind an operator key (``obs_access_key`` or
    # PIO_OBS_ACCESS_KEY), which then gates everything except /healthz.
    obs_access_key = obs_access_key or os.environ.get("PIO_OBS_ACCESS_KEY")
    add_observability_routes(
        app,
        registry,
        access_key=obs_access_key,
        debug_routes=obs_access_key is not None,
        readiness={
            "event_store": _event_store_ready,
            "metadata_store": _metadata_ready,
        },
        quality=quality,
        costs=costs,
    )
    m_ingested = registry.counter(
        "pio_events_ingested_total",
        "Events accepted by the event server, by event name",
        labelnames=("event",),
    )

    def _store_seam(app_id: int) -> None:
        """The ``eventstore.write`` fault seam, checked with the write's
        ingest-gate slot held: a latency rule stalls exactly like a slow
        store (saturation, then 503 shed); raising kinds surface as the
        store being down (retryable 503)."""
        if faults.ACTIVE is not None:
            faults.ACTIVE.check("eventstore.write", str(app_id))

    def authed(handler):
        def wrapped(req: Request) -> Response:
            try:
                auth = _authenticate(storage, req)
            except AuthError as e:
                return error_response(e.status, str(e))
            except _STORE_UNAVAILABLE as e:
                # key lookup needs the metadata store: down -> retryable
                return _unavailable_response(e)
            # every authenticated call runs under a bound RequestCost, so
            # the parquet tier's note_storage_read bills reads (find/get)
            # to the calling app — ingest's "who costs what" half
            with request_cost(
                f"app:{auth.app_id}",
                _cost_route(req.path),
                "ingest",
                ledger=costs,
            ):
                return handler(req, auth)

        return wrapped

    # label-cardinality guard: event names are client-supplied (some apps
    # embed ids in them) and registry children are never evicted — past the
    # cap, new names collapse into one overflow series
    seen_event_labels: set[str] = set()
    _MAX_EVENT_LABELS = 100

    def bookkeep(auth: AuthData, status: int, event: Event) -> None:
        name = event.event
        if name not in seen_event_labels:
            if len(seen_event_labels) >= _MAX_EVENT_LABELS:
                name = "_other"
            else:
                seen_event_labels.add(name)
        m_ingested.labels(name).inc()
        if quality.is_feedback(event.event):
            # the join key preference order: the X-Pio-Request-Id the client
            # echoed on this ingest call (bound to the request context by
            # the front end), then the event's own prId / pioRequestId,
            # then entity id within the join window (observe_feedback)
            quality.observe_feedback(
                event, request_id=get_request_id(), app=auth.app_id
            )
        if hourly is not None:
            hourly.update(
                auth.app_id,
                status,
                event.entity_type,
                event.target_entity_type,
                event.event,
            )

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        return json_response(200, {"status": "alive"})

    # -- single event CRUD ---------------------------------------------------
    @app.route("POST", "/events\\.json")
    @gated_write
    @authed
    def post_event(req: Request, auth: AuthData) -> Response:
        try:
            payload = req.json()
            if not isinstance(payload, dict):
                raise EventValidationError("request body must be a JSON object")
            event = Event.from_api_dict(payload)
        except EventValidationError as e:
            return error_response(400, str(e))
        except Exception as e:
            return error_response(400, f"invalid JSON: {e}")
        if auth.events and event.event not in auth.events:
            return error_response(403, f"{event.event} events are not allowed")
        try:
            plugins.process_input(auth.app_id, auth.channel_id, event)
        except Exception as e:  # an input blocker rejected the event
            return error_response(403, f"rejected by plugin: {e}")
        try:
            _store_seam(auth.app_id)
            event_id = levents.insert(event, auth.app_id, auth.channel_id)
        except _STORE_UNAVAILABLE as e:
            return _unavailable_response(e)
        bookkeep(auth, 201, event)
        return json_response(201, {"eventId": event_id})

    @app.route("GET", "/events\\.json")
    @authed
    def get_events(req: Request, auth: AuthData) -> Response:
        q = req.query
        reversed_ = q.get("reversed", "false").lower() == "true"
        if reversed_ and not (q.get("entityType") and q.get("entityId")):
            return error_response(
                400,
                "the parameter reversed can only be used with both entityType "
                "and entityId specified.",
            )
        try:
            filt = EventFilter(
                start_time=(
                    parse_event_time(q["startTime"]) if "startTime" in q else None
                ),
                until_time=(
                    parse_event_time(q["untilTime"]) if "untilTime" in q else None
                ),
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=(q["event"],) if "event" in q else None,
                target_entity_type=q.get("targetEntityType"),
                target_entity_id=q.get("targetEntityId"),
                limit=int(q.get("limit", 20)),
                reversed=reversed_,
            )
        except Exception as e:
            return error_response(400, str(e))
        events = list(levents.find(auth.app_id, auth.channel_id, filt))
        if not events:
            return error_response(404, "Not Found")
        return json_response(200, [e.to_api_dict() for e in events])

    @app.route("GET", "/events/(?P<event_id>[^/]+)\\.json")
    @authed
    def get_event(req: Request, auth: AuthData) -> Response:
        e = levents.get(req.params["event_id"], auth.app_id, auth.channel_id)
        if e is None:
            return error_response(404, "Not Found")
        return json_response(200, e.to_api_dict())

    @app.route("DELETE", "/events/(?P<event_id>[^/]+)\\.json")
    @gated_write
    @authed
    def delete_event(req: Request, auth: AuthData) -> Response:
        found = levents.delete(req.params["event_id"], auth.app_id, auth.channel_id)
        if found:
            return json_response(200, {"message": "Found"})
        return error_response(404, "Not Found")

    # -- batch ---------------------------------------------------------------
    @app.route("POST", "/batch/events\\.json")
    @gated_write
    @authed
    def post_batch(req: Request, auth: AuthData) -> Response:
        try:
            payload = req.json()
        except Exception as e:
            return error_response(400, f"invalid JSON: {e}")
        if not isinstance(payload, list):
            return error_response(400, "request body must be a JSON array")
        if len(payload) > 50:
            return error_response(
                400,
                "Batch request must have less than or equal to 50 events",
            )
        results: list[dict[str, Any]] = []
        for item in payload:
            try:
                event = Event.from_api_dict(item)
            except Exception as e:
                # any undeserializable item -> per-item 400, batch still 200
                results.append({"status": 400, "message": str(e)})
                continue
            if auth.events and event.event not in auth.events:
                results.append(
                    {
                        "status": 403,
                        "message": f"{event.event} events are not allowed",
                    }
                )
                continue
            try:
                plugins.process_input(auth.app_id, auth.channel_id, event)
            except Exception as e:
                results.append(
                    {"status": 403, "message": f"rejected by plugin: {e}"}
                )
                continue
            try:
                _store_seam(auth.app_id)
                event_id = levents.insert(event, auth.app_id, auth.channel_id)
            except _STORE_UNAVAILABLE as e:
                # per-item 503: the batch contract stays "one status per
                # event", and the store being down is retryable, not a 500
                results.append({"status": 503, "message": str(e)})
                continue
            except Exception as e:
                results.append({"status": 500, "message": str(e)})
                continue
            bookkeep(auth, 201, event)
            results.append({"status": 201, "eventId": event_id})
        return json_response(200, results)

    # -- plugins (EventServer.scala:154-206) ---------------------------------
    @app.route("GET", "/plugins\\.json")
    @authed
    def list_plugins(req: Request, auth: AuthData) -> Response:
        return json_response(200, {"plugins": plugins.descriptions()})

    @app.route(
        "GET",
        "/plugins/(?P<ptype>[^/]+)/(?P<pname>[^/]+)(?P<rest>/.*)?",
    )
    @authed
    def plugin_rest(req: Request, auth: AuthData) -> Response:
        return plugins.rest_response(
            req.params["ptype"], req.params["pname"],
            req.params.get("rest") or "/", req.query,
        )

    # -- stats ---------------------------------------------------------------
    @app.route("GET", "/stats\\.json")
    @authed
    def get_stats(req: Request, auth: AuthData) -> Response:
        if hourly is None:
            return error_response(
                404,
                "To see stats, launch Event Server with --stats argument.",
            )
        return json_response(200, hourly.get(auth.app_id))

    # -- webhooks ------------------------------------------------------------
    _json_connectors = json_connectors()
    _form_connectors = form_connectors()

    def _webhook_insert(auth: AuthData, event: Event) -> Response:
        try:
            plugins.process_input(auth.app_id, auth.channel_id, event)
        except Exception as e:
            return error_response(403, f"rejected by plugin: {e}")
        try:
            _store_seam(auth.app_id)
            event_id = levents.insert(event, auth.app_id, auth.channel_id)
        except _STORE_UNAVAILABLE as e:
            return _unavailable_response(e)
        bookkeep(auth, 201, event)
        return json_response(201, {"eventId": event_id})

    @app.route("POST", "/webhooks/(?P<web>[^/]+)\\.json")
    @gated_write
    @authed
    def post_webhook_json(req: Request, auth: AuthData) -> Response:
        web = req.params["web"]
        connector = _json_connectors.get(web)
        if connector is None:
            return error_response(
                404, f"webhooks connection for {web} is not supported."
            )
        try:
            payload = req.json()
            if not isinstance(payload, dict):
                raise ConnectorException("payload must be a JSON object")
            event = to_event(connector, payload)
        except ConnectorException as e:
            return error_response(400, str(e))
        except Exception as e:
            return error_response(400, f"invalid JSON: {e}")
        return _webhook_insert(auth, event)

    @app.route("GET", "/webhooks/(?P<web>[^/]+)\\.json")
    @authed
    def get_webhook_json(req: Request, auth: AuthData) -> Response:
        if req.params["web"] not in _json_connectors:
            return error_response(
                404,
                f"webhooks connection for {req.params['web']} is not supported.",
            )
        return json_response(200, {"message": "Ok"})

    @app.route("POST", "/webhooks/(?P<web>[^/]+)\\.form")
    @gated_write
    @authed
    def post_webhook_form(req: Request, auth: AuthData) -> Response:
        web = req.params["web"]
        connector = _form_connectors.get(web)
        if connector is None:
            return error_response(
                404, f"webhooks connection for {web} is not supported."
            )
        try:
            event = to_event(connector, req.form())
        except ConnectorException as e:
            return error_response(400, str(e))
        except UnicodeDecodeError as e:
            return error_response(400, f"invalid form body: {e}")
        return _webhook_insert(auth, event)

    @app.route("GET", "/webhooks/(?P<web>[^/]+)\\.form")
    @authed
    def get_webhook_form(req: Request, auth: AuthData) -> Response:
        if req.params["web"] not in _form_connectors:
            return error_response(
                404,
                f"webhooks connection for {req.params['web']} is not supported.",
            )
        return json_response(200, {"message": "Ok"})

    return app


def create_event_server(
    host: str = "0.0.0.0",
    port: int = 7070,
    storage: StorageRuntime | None = None,
    stats: bool = False,
    plugins: "PluginContext | None" = None,
) -> AppServer:
    """Bind the event server (EventServer.createEventServer:528)."""
    return AppServer(
        create_event_server_app(storage, stats=stats, plugins=plugins), host, port
    )
