"""Event-server bookkeeping counters (data/api/Stats.scala:47-112).

Counts per-app (entityType, targetEntityType, event) triples and HTTP status
codes, with an hourly cutoff: ``update`` rolls the current window when the
hour changes, keeping the previous hour's frozen snapshot queryable — the
StatsActor's HourlyStats behavior (StatsActor.scala:76)."""

from __future__ import annotations

import threading
from collections import Counter
from dataclasses import dataclass, field
from datetime import datetime, timedelta, timezone
from typing import Any


def _now() -> datetime:
    return datetime.now(tz=timezone.utc)


def _hour_floor(t: datetime) -> datetime:
    return t.replace(minute=0, second=0, microsecond=0)


@dataclass
class StatsWindow:
    start_time: datetime
    end_time: datetime | None = None
    # (appId, entityType, targetEntityType|None, event) -> count
    ete_count: Counter = field(default_factory=Counter)
    # (appId, status) -> count
    status_count: Counter = field(default_factory=Counter)

    def snapshot(self, app_id: int) -> dict[str, Any]:
        return {
            "startTime": self.start_time.isoformat(),
            "endTime": self.end_time.isoformat() if self.end_time else None,
            "basic": [
                {
                    "entityType": et,
                    "targetEntityType": tet,
                    "event": ev,
                    "count": c,
                }
                for (aid, et, tet, ev), c in sorted(
                    self.ete_count.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2] or "", kv[0][3])
                )
                if aid == app_id
            ],
            "statusCode": [
                {"status": status, "count": c}
                for (aid, status), c in sorted(self.status_count.items())
                if aid == app_id
            ],
        }


class HourlyStats:
    """Thread-safe current + previous hourly windows."""

    def __init__(self):
        self._lock = threading.Lock()
        now = _now()
        self.current = StatsWindow(start_time=_hour_floor(now))
        self.previous: StatsWindow | None = None

    def update(
        self,
        app_id: int,
        status: int,
        entity_type: str,
        target_entity_type: str | None,
        event_name: str,
    ) -> None:
        with self._lock:
            now = _now()
            hour = _hour_floor(now)
            if hour > self.current.start_time:
                # the frozen window covers exactly its own hour, not the
                # whole idle gap
                self.current.end_time = self.current.start_time + timedelta(
                    hours=1
                )
                # only an ADJACENT window is "the previous hour"; after a
                # multi-hour idle gap the prior hour had no traffic, so a
                # stale window must not be served as previousHour
                self.previous = (
                    self.current
                    if hour - self.current.start_time == timedelta(hours=1)
                    else None
                )
                self.current = StatsWindow(start_time=hour)
            self.current.ete_count[
                (app_id, entity_type, target_entity_type, event_name)
            ] += 1
            self.current.status_count[(app_id, status)] += 1

    def get(self, app_id: int) -> dict[str, Any]:
        with self._lock:
            out = {"currentHour": self.current.snapshot(app_id)}
            if self.previous is not None:
                out["previousHour"] = self.previous.snapshot(app_id)
            return out
