"""Evaluation dashboard (:9000).

Parity with tools/dashboard/Dashboard.scala:47-120: an HTML index of
completed evaluations (newest first) with their params and metric scores, and
a per-instance detail page rendering the evaluator's stored HTML
(CoreWorkflow persists one-liner/HTML/JSON results onto the
EvaluationInstance row, CoreWorkflow.scala:144-155).
"""

from __future__ import annotations

import html
import json
import os
from urllib.parse import quote

from predictionio_tpu.data.storage.config import StorageRuntime, get_storage
from predictionio_tpu.obs.capacity import capacity_snapshot
from predictionio_tpu.obs.device import device_snapshot
from predictionio_tpu.obs.http import add_observability_routes
from predictionio_tpu.obs.metrics import REGISTRY, MetricsRegistry
from predictionio_tpu.obs.quality import QualityMonitor, default_quality
from predictionio_tpu.obs.slo import run_readiness
from predictionio_tpu.obs.timeline import (
    Timeline,
    TraceAssemblyError,
    TraceNode,
    collect_trace,
)
from predictionio_tpu.obs.tracing import recent_traces
from predictionio_tpu.server.httpd import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    error_response,
)


#: eight-level unicode sparkline alphabet (min → max of the series)
_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float]) -> str:
    """Render a sampled series as a fixed-height unicode sparkline."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    top = len(_SPARK_CHARS) - 1
    return "".join(
        _SPARK_CHARS[int((v - lo) / span * top)] for v in values
    )


def _metrics_table_html(registry: MetricsRegistry) -> str:
    """The registry as an HTML table: counters/gauges with their value,
    histograms with count + p50/p95/p99 (computed from the log buckets),
    plus a per-series sparkline from the scrape-fed history ring — which is
    what gives the serving-latency rows their trend at a glance."""
    rows = []
    for name, fam in sorted(registry.render_json().items()):
        for s in fam["series"]:
            label_values = tuple(str(v) for v in s["labels"].values())
            labels = ",".join(f"{k}={v}" for k, v in s["labels"].items())
            if fam["type"] in ("counter", "gauge"):
                detail = f"{s['value']:g}"
            else:
                detail = (
                    f"n={s['count']} p50={s['p50']:.6f} "
                    f"p95={s['p95']:.6f} p99={s['p99']:.6f}"
                )
            spark = _sparkline(registry.history.series(name, label_values))
            rows.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td>{html.escape(labels)}</td>"
                f"<td>{html.escape(fam['type'])}</td>"
                f"<td>{html.escape(detail)}</td>"
                f"<td>{html.escape(spark)}</td></tr>"
            )
    return (
        "<h2>Metrics</h2><table border='1'>"
        "<tr><th>metric</th><th>labels</th><th>type</th><th>value</th>"
        "<th>trend</th></tr>"
        + "".join(rows)
        + "</table>"
    )


def _quality_html(quality: QualityMonitor, registry: MetricsRegistry) -> str:
    """Model-quality panel: drift state per distribution and the rolling
    online metrics per engine variant, with sparklines from the history
    ring (``pio_online_metric{variant,metric}``).

    Side effect: the render IS a scrape — ``snapshot()`` refreshes the
    quality gauges and the history ring then samples the registry, in that
    order, so every trend tail on the page (this panel and the metrics
    table below it) matches the value column instead of lagging a render.
    """
    snap = quality.snapshot()
    registry.history.sample(registry)
    drift = snap["drift"]
    drift_rows = "".join(
        f"<tr><td>{html.escape(name)}</td>"
        f"<td>{html.escape(d['state'])}</td>"
        f"<td>{d['psi']:.4f}</td><td>{d['ks']:.4f}</td>"
        f"<td>{d['windows']}</td><td>{d['transitions']}</td>"
        f"<td>{html.escape(_sparkline(registry.history.series('pio_drift_psi', (name,))))}</td></tr>"
        for name, d in drift["distributions"].items()
    )
    variant_rows = []
    for variant, v in snap["variants"].items():
        for metric, value in v["metrics"].items():
            spark = _sparkline(
                registry.history.series("pio_online_metric", (variant, metric))
            )
            variant_rows.append(
                f"<tr><td>{html.escape(variant)}</td>"
                f"<td>{html.escape(metric)}</td>"
                f"<td>{'n/a' if value is None else f'{value:.4f}'}</td>"
                f"<td>{html.escape(spark)}</td></tr>"
            )
        variant_rows.append(
            f"<tr><td>{html.escape(variant)}</td><td>volume</td>"
            f"<td>{v['predictions']} predictions, {v['joined']} joined</td>"
            f"<td></td></tr>"
        )
    return (
        f"<h2>Model quality</h2><p>drift: <b>{html.escape(drift['state'])}</b>"
        f", prediction log {snap['log']['size']}/{snap['log']['capacity']}</p>"
        "<table border='1'><tr><th>distribution</th><th>state</th>"
        "<th>psi</th><th>ks</th><th>windows</th><th>transitions</th>"
        "<th>trend</th></tr>"
        + drift_rows
        + "</table><table border='1'><tr><th>variant</th><th>metric</th>"
        "<th>value</th><th>trend</th></tr>"
        + "".join(variant_rows)
        + "</table>"
    )


def _efficiency_html(registry: MetricsRegistry) -> str:
    """Device-efficiency panel: achieved-vs-peak per jitted entry point
    (the /efficiency.json surface, human-shaped) with trend sparklines
    from the scrape-fed history ring, plus any active recompile storm —
    the at-a-glance answer to "is the chip earning its keep"."""
    snap = device_snapshot()
    peaks = snap["peaks"]
    rows = []
    for fn, entry in sorted(snap["functions"].items()):
        if "achieved_gbps" not in entry:
            continue  # cost known but never timed: nothing to chart yet
        spark_gbps = _sparkline(
            registry.history.series("pio_device_achieved_gbps", (fn,))
        )
        rows.append(
            f"<tr><td>{html.escape(fn)}</td>"
            f"<td>{entry['calls']}</td>"
            f"<td>{entry['achieved_gbps']:.3f}</td>"
            f"<td>{entry['utilization_hbm']:.2%}</td>"
            f"<td>{entry['achieved_tflops']:.4f}</td>"
            f"<td>{entry['utilization_mxu']:.2%}</td>"
            f"<td>{html.escape(entry.get('source', '?'))}</td>"
            f"<td>{html.escape(spark_gbps)}</td></tr>"
        )
    storms = snap["recompiles"]["active_storms"]
    storm_note = (
        "<p><b>RECOMPILE STORM:</b> "
        + ", ".join(html.escape(fn) for fn in sorted(storms))
        + " — traffic is churning shapes; every wave pays an XLA "
        "compile</p>"
        if storms
        else ""
    )
    shards = snap.get("shards") or {}
    shard_rows = []
    for fn, per_dev in sorted(shards.get("functions", {}).items()):
        for device, entry in sorted(per_dev.items()):
            shard_rows.append(
                f"<tr><td>{html.escape(fn)}</td>"
                f"<td>{html.escape(device)}</td>"
                f"<td>{entry.get('bytes', 0.0):.0f}</td>"
                f"<td>{entry.get('waves', 0)}</td>"
                f"<td>{entry.get('seconds', 0.0):.4f}</td></tr>"
            )
    shard_html = (
        "<h3>Mesh shards</h3><p>mesh: "
        + html.escape(", ".join(shards.get("devices", [])))
        + "</p><table border='1'><tr><th>fn</th><th>device</th>"
        "<th>bytes</th><th>waves</th><th>seconds</th></tr>"
        + "".join(shard_rows)
        + "</table>"
        if shard_rows
        else ""
    )
    return (
        f"<h2>Device efficiency</h2><p>platform: "
        f"{html.escape(str(snap['platform']))}, peaks: "
        f"{peaks['hbm_gbps']:g} GB/s HBM / {peaks['tflops']:g} TFLOP/s "
        f"({html.escape(str(peaks['source']))})</p>"
        + storm_note
        + "<table border='1'><tr><th>fn</th><th>calls</th>"
        "<th>GB/s</th><th>HBM util</th><th>TFLOP/s</th><th>MXU util</th>"
        "<th>cost source</th><th>trend</th></tr>"
        + "".join(rows)
        + "</table>"
        + shard_html
    )


def _traces_table_html(n: int = 15, access_key: str | None = None) -> str:
    """Recent root spans; rows with a request id link to the matching
    flight-recorder entry for the full per-request record, and rows with a
    trace id link to the ASSEMBLED cross-process waterfall (``/trace/<id>``)
    — not just this process's fragment of it.  On a key-gated dashboard
    every link carries the accessKey (the Dashboard.scala:47 link-parity
    rationale the query-param transport exists for) so clicking through
    from an authenticated page doesn't 401."""
    key_amp = f"&accessKey={quote(access_key)}" if access_key else ""
    key_q = f"?accessKey={quote(access_key)}" if access_key else ""
    rows = []
    for t in recent_traces(n):
        rid = t.get("request_id") or ""
        rid_cell = (
            f"<a href='/debug/flight.json?request_id={quote(rid)}"
            f"{key_amp}'>{html.escape(rid)}</a>"
            if rid
            else ""
        )
        tid = t.get("trace_id") or ""
        tid_cell = (
            f"<a href='/trace/{quote(tid)}{key_q}'>{html.escape(tid)}</a>"
            if tid
            else ""
        )
        # the decision-provenance click-through: request_id= is already a
        # query param, so the key joins with '&' (key_amp, never key_q —
        # a second '?' would truncate the gated link's request id)
        explain_cell = (
            f"<a href='/explain.json?request_id={quote(rid)}"
            f"{key_amp}'>why</a>"
            if rid
            else ""
        )
        children = ", ".join(
            c.get("name", "") for c in t.get("children", [])
        )
        rows.append(
            f"<tr><td>{html.escape(t.get('name', ''))}</td>"
            f"<td>{t.get('duration_s', 0):.6f}</td>"
            f"<td>{rid_cell}</td>"
            f"<td>{tid_cell}</td>"
            f"<td>{explain_cell}</td>"
            f"<td>{html.escape(t.get('error') or '')}</td>"
            f"<td>{html.escape(children)}</td></tr>"
        )
    return (
        "<h2>Recent traces</h2><table border='1'>"
        "<tr><th>span</th><th>seconds</th><th>request</th><th>trace</th>"
        "<th>explain</th><th>error</th><th>children</th></tr>"
        + "".join(rows)
        + "</table>"
    )


def _waterfall_html(tl: Timeline, access_key: str | None = None) -> str:
    """One assembled trace as an HTML waterfall: a lane per process (device
    tracks indented under theirs), each span a positioned bar over the
    trace's full wall-clock extent plus the indented name/timing text the
    text renderer prints.  Pure inline-styled HTML — the dashboard has no
    static assets."""
    t0 = tl.t0
    end = max(
        (n.start_s + n.duration_s for n in tl.nodes.values()), default=t0
    )
    span_ms = max((end - t0) * 1e3, 1e-6)
    key_amp = f"&accessKey={quote(access_key)}" if access_key else ""
    parts = [
        f"<h2>Trace {html.escape(tl.trace_id)}</h2>"
        f"<p>{len(tl.processes)} process(es), {tl.span_count} span(s), "
        f"{span_ms:.1f} ms"
        f" — <a href='/spans.json?trace_id={quote(tl.trace_id)}{key_amp}'>"
        "this process's raw fragments</a>, "
        f"<a href='/trace/{quote(tl.trace_id)}?format=perfetto{key_amp}'>"
        "Perfetto JSON</a> (open in https://ui.perfetto.dev); assemble "
        f"across daemons with <code>pio trace {html.escape(tl.trace_id)} "
        "--from URL,URL --perfetto out.json</code></p>"
    ]
    for err in tl.source_errors:
        parts.append(f"<p><b>source error:</b> {html.escape(err)}</p>")
    by_process: dict[str, list[tuple[int, TraceNode]]] = {}

    def index(node: TraceNode, depth: int) -> None:
        by_process.setdefault(node.process, []).append((depth, node))
        for c in node.children:
            index(c, depth + 1)

    for root in tl.roots:
        index(root, 0)
    for proc in tl.processes:
        rows = []
        for depth, node in by_process.get(proc, []):
            left = (node.start_s - t0) * 1e3 / span_ms * 100.0
            width = max(node.duration_s * 1e3 / span_ms * 100.0, 0.2)
            device = node.track != "spans"
            color = "#8bc" if device else "#c86"
            label = (
                f"{'&nbsp;' * (2 * depth)}{html.escape(node.name)}"
                f"{' [' + html.escape(node.track) + ']' if device else ''}"
                f" +{(node.start_s - t0) * 1e3:.2f}ms "
                f"{node.duration_s * 1e3:.3f}ms"
                f"{' ORPHAN' if node.orphan else ''}"
                + (
                    " ERROR: " + html.escape(str(node.fragment["error"]))
                    if node.fragment.get("error")
                    else ""
                )
            )
            rows.append(
                "<tr>"
                f"<td style='white-space:nowrap'>{label}</td>"
                "<td style='width:50%'><div style='position:relative;"
                "height:10px;background:#eee'>"
                f"<div style='position:absolute;left:{left:.2f}%;"
                f"width:{width:.2f}%;height:10px;background:{color}'>"
                "</div></div></td></tr>"
            )
        parts.append(
            f"<h3>{html.escape(proc)}</h3>"
            "<table border='0' style='width:100%'>" + "".join(rows)
            + "</table>"
        )
    return "".join(parts)


def _health_html(app: HTTPApp) -> str:
    """SLO window + readiness checks as a panel (the /healthz, /readyz,
    /slo.json surface, human-shaped)."""
    slo = app.slo.snapshot()
    ready, checks = run_readiness(app.readiness)
    slo_rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in slo.items()
    )
    check_rows = "".join(
        f"<tr><td>{html.escape(name)}</td>"
        f"<td>{'ok' if ok else 'FAILING'}</td></tr>"
        for name, ok in checks.items()
    )
    return (
        f"<h2>Health</h2><p>status: <b>{html.escape(slo['status'])}</b>, "
        f"ready: <b>{'yes' if ready else 'NO'}</b></p>"
        "<table border='1'><tr><th>slo</th><th>value</th></tr>"
        + slo_rows
        + "</table><table border='1'><tr><th>readiness check</th>"
        "<th>state</th></tr>"
        + check_rows
        + "</table>"
    )


def _capacity_html(app: HTTPApp) -> str:
    """Capacity panel: the headroom model (obs/capacity.py) over this
    process's registry — max-sustainable QPS, which ceiling binds, and the
    recommended replica count an autoscaler would act on."""
    snap = capacity_snapshot(app, REGISTRY)
    headroom = snap.get("headroom_frac")
    inputs = snap.get("inputs", {})
    input_rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in inputs.items()
        if v is not None
    )
    ceiling_rows = "".join(
        f"<tr><td>{html.escape(name)}"
        f"{' (binding)' if name == snap.get('binding_ceiling') else ''}</td>"
        f"<td>{qps:g} qps</td></tr>"
        for name, qps in snap.get("ceilings_qps", {}).items()
    )
    caveats = "".join(
        f"<li>{html.escape(c)}</li>" for c in snap.get("caveats", [])
    )
    return (
        "<h2>Capacity</h2><p>headroom: <b>"
        + (f"{headroom:.1%}" if headroom is not None else "unknown")
        + "</b>, max sustainable: <b>"
        + (
            f"{snap['max_sustainable_qps']:g} qps"
            if snap.get("max_sustainable_qps") is not None
            else "unknown"
        )
        + f"</b>, recommended replicas: "
        f"<b>{snap.get('recommended_replicas') or '?'}</b>, "
        f"scale hint: <b>{html.escape(str(snap.get('scale_hint')))}</b></p>"
        "<table border='1'><tr><th>ceiling</th><th>qps</th></tr>"
        + ceiling_rows
        + "</table><table border='1'><tr><th>input</th><th>value</th></tr>"
        + input_rows
        + "</table>"
        + (f"<ul>{caveats}</ul>" if caveats else "")
    )


def _fleet_html(fleet_url: str, access_key: str | None = None) -> str:
    """Fleet panel: the router's /fleet.json membership registry — who the
    replicas are, which are routable, and what each last said about its
    capacity.  A dead router costs one bounded fetch and renders as a
    one-line notice (the dashboard must not die with the fleet)."""
    import urllib.request

    headers = {}
    if access_key:
        headers["Authorization"] = f"Bearer {access_key}"
    try:
        req = urllib.request.Request(
            fleet_url.rstrip("/") + "/fleet.json", headers=headers
        )
        with urllib.request.urlopen(req, timeout=3.0) as r:
            body = json.loads(r.read().decode("utf-8"))
    except Exception as e:
        return (
            "<h2>Fleet</h2><p>router at "
            f"<code>{html.escape(fleet_url)}</code> unreachable: "
            f"{html.escape(str(e))}</p>"
        )
    rows = []
    for rep in body.get("replicas", []):
        state = "ok"
        if rep.get("draining"):
            state = "draining"
        elif not rep.get("healthy"):
            state = "EJECTED"
        elif rep.get("breaker") == "open":
            state = "BREAKER-OPEN"
        cap = rep.get("capacity") or {}
        headroom = cap.get("headroom_frac")
        rows.append(
            f"<tr><td>{html.escape(str(rep.get('replica')))}</td>"
            f"<td>{state}</td>"
            f"<td>{html.escape(str(rep.get('breaker')))}</td>"
            f"<td>{rep.get('inflight', 0)}</td>"
            f"<td>{_esc_num(cap.get('max_sustainable_qps'))}</td>"
            "<td>"
            + (
                f"{headroom:.1%}"
                if isinstance(headroom, (int, float))
                else "n/a"
            )
            + "</td></tr>"
        )
    auto = body.get("autoscaler") or {}
    auto_line = ""
    if auto:
        pol = auto.get("policy", {})
        auto_line = (
            "<p>autoscaler: "
            f"[{pol.get('min_replicas')}..{pol.get('max_replicas')}] "
            + (
                f"pinned at {auto['target_override']}"
                if auto.get("target_override") is not None
                else "capacity-driven"
            )
            + "</p>"
        )
    return (
        f"<h2>Fleet</h2><p>{body.get('total', 0)} replicas, "
        f"<b>{body.get('routable', 0)}</b> routable "
        f"(router: <code>{html.escape(fleet_url)}</code>)</p>"
        "<table border='1'><tr><th>replica</th><th>state</th><th>breaker</th>"
        "<th>inflight</th><th>max qps</th><th>headroom</th></tr>"
        + "".join(rows)
        + "</table>"
        + auto_line
    )


def _esc_num(v) -> str:
    return f"{v:g}" if isinstance(v, (int, float)) else "n/a"


def _tenants_html(serving_url: str, access_key: str | None = None) -> str:
    """Tenants panel: a running replica's /tenants.json — one row per
    resident tenant (SLO state, quota burn, resident HBM bytes, in-flight
    count, degraded reasons).  A dead replica costs one bounded fetch and
    renders as a one-line notice (the dashboard must not die with it)."""
    import urllib.request

    headers = {}
    if access_key:
        headers["Authorization"] = f"Bearer {access_key}"
    base = serving_url.rstrip("/")
    try:
        req = urllib.request.Request(
            base + "/tenants.json", headers=headers
        )
        with urllib.request.urlopen(req, timeout=3.0) as r:
            body = json.loads(r.read().decode("utf-8"))
    except Exception as e:
        return (
            "<h2>Tenants</h2><p>replica at "
            f"<code>{html.escape(serving_url)}</code> unreachable: "
            f"{html.escape(str(e))}</p>"
        )
    # gated drill-down links reuse the single-`?` access-key join: the key
    # (when configured) claims the `?`, every further param joins with `&`
    # — a second `?` would truncate the query string at the replica
    key_q = f"?accessKey={quote(access_key)}" if access_key else ""
    amp = "&" if access_key else "?"
    rows = []
    for t in body.get("tenants", []):
        slo = t.get("slo") or {}
        quota = t.get("quota") or {}
        degraded = ",".join(t.get("degraded") or []) or "-"
        name = str(t.get("app"))
        link = f"{base}/tenants.json{key_q}{amp}app={quote(name)}"
        rows.append(
            f"<tr><td><a href='{html.escape(link)}'>"
            f"{html.escape(name)}</a></td>"
            f"<td>{html.escape(str(slo.get('status')))}</td>"
            f"<td>{_esc_num(slo.get('availability'))}</td>"
            f"<td>{quota.get('denied', 0) if quota else '-'}</td>"
            f"<td>{t.get('hbm_bytes', 0)}</td>"
            f"<td>{t.get('inflight', 0)}</td>"
            f"<td>{html.escape(degraded)}</td></tr>"
        )
    budget = body.get("hbm_budget_bytes")
    return (
        f"<h2>Tenants</h2><p>{body.get('count', 0)} resident, HBM "
        f"{body.get('hbm_resident_bytes', 0)}"
        + (f"/{budget}" if budget else "")
        + f" bytes (replica: <code>{html.escape(serving_url)}</code>)</p>"
        "<table border='1'><tr><th>app</th><th>slo</th>"
        "<th>availability</th><th>quota denied</th><th>hbm bytes</th>"
        "<th>inflight</th><th>degraded</th></tr>"
        + "".join(rows)
        + "</table>"
    )


def _alerts_html(
    app: HTTPApp, fleet_url: str | None = None, access_key: str | None = None
) -> str:
    """Alerts panel: the evaluator's firing/pending table (age + rule +
    value, with links to the matching incident bundle and the assembled
    ``/trace/<id>`` waterfall where an exemplar exists) and the recorded
    Incidents list.  With a fleet router configured, the local snapshot is
    swapped for the router's federated /alerts.json so the panel shows the
    whole fleet replica-tagged."""
    key_q = f"?accessKey={quote(access_key)}" if access_key else ""
    evaluator = getattr(app, "alerts", None)
    snap: dict = {}
    source = "local"
    if fleet_url:
        import urllib.request

        headers = {}
        if access_key:
            headers["Authorization"] = f"Bearer {access_key}"
        try:
            req = urllib.request.Request(
                fleet_url.rstrip("/") + "/alerts.json", headers=headers
            )
            with urllib.request.urlopen(req, timeout=3.0) as r:
                snap = json.loads(r.read().decode("utf-8"))
            source = f"fleet router {fleet_url}"
        except Exception as e:
            snap = {}
            source = f"router alerts unreachable ({e}); local state below"
    if not snap and evaluator is not None:
        snap = evaluator.snapshot()
    recorder = getattr(app, "incidents", None)
    incidents = recorder.list() if recorder is not None else []
    by_rule = {}
    for inc in incidents:
        by_rule.setdefault(inc.get("rule"), inc)
    rows = []
    for a in snap.get("alerts", []):
        inc = by_rule.get(a.get("rule"))
        inc_cell = (
            f"<a href='/incidents/{quote(str(inc.get('id')))}.json{key_q}'>"
            f"{html.escape(str(inc.get('id')))}</a>"
            if inc and inc.get("id")
            else ""
        )
        tid = (inc or {}).get("exemplar_trace_id") or ""
        trace_cell = (
            f"<a href='/trace/{quote(str(tid))}{key_q}'>{html.escape(str(tid))}</a>"
            if tid
            else ""
        )
        age = a.get("age_s")
        rows.append(
            f"<tr><td><b>{html.escape(str(a.get('state', '')).upper())}</b></td>"
            f"<td>{html.escape(str(a.get('rule')))}</td>"
            f"<td>{html.escape(str(a.get('key') or ''))}</td>"
            f"<td>{html.escape(str(a.get('replica') or ''))}</td>"
            f"<td>{html.escape(str(a.get('value')))}</td>"
            + (
                f"<td>{age:.0f}s</td>"
                if isinstance(age, (int, float))
                else "<td></td>"
            )
            + f"<td>{html.escape(str(a.get('severity')))}</td>"
            f"<td>{inc_cell}</td><td>{trace_cell}</td></tr>"
        )
    inc_rows = "".join(
        f"<tr><td><a href='/incidents/{quote(str(i.get('id')))}.json{key_q}'>"
        f"{html.escape(str(i.get('id')))}</a></td>"
        f"<td>{html.escape(str(i.get('rule')))}</td>"
        f"<td>{html.escape(str(i.get('severity')))}</td>"
        f"<td>{i.get('spans', 0)}</td>"
        f"<td>{html.escape(str(i.get('exemplar_trace_id') or ''))}</td></tr>"
        for i in incidents[:15]
    )
    return (
        f"<h2>Alerts</h2><p><b>{snap.get('firing', 0)}</b> firing, "
        f"{snap.get('pending', 0)} pending "
        f"({len(snap.get('rules', []) or [])} rules; source: "
        f"{html.escape(source)})</p>"
        + "".join(
            f"<p><b>source error:</b> {html.escape(str(e))}</p>"
            for e in snap.get("source_errors", [])
        )
        + "<table border='1'><tr><th>state</th><th>rule</th><th>key</th>"
        "<th>replica</th><th>value</th><th>age</th><th>severity</th>"
        "<th>incident</th><th>trace</th></tr>"
        + "".join(rows)
        + "</table>"
        "<h3>Incidents</h3><table border='1'><tr><th>bundle</th>"
        "<th>rule</th><th>severity</th><th>spans</th><th>exemplar</th></tr>"
        + inc_rows
        + "</table><p>replay offline: <code>pio incident show &lt;id&gt;"
        "</code> · <code>pio trace &lt;trace-id&gt; --file "
        "&lt;bundle.json&gt;</code></p>"
    )


def _profiling_html(access_key: str | None = None) -> str:
    """Profiling panel: the on-demand device profile and the continuous
    host stack sampler, side by side — one answers "what is the device
    doing", the other "where is the host spending its milliseconds", and a
    slow request usually needs both."""
    qs = f"?accessKey={quote(access_key)}" if access_key else ""
    amp = "&" if access_key else "?"
    return (
        "<h2>Profiling</h2><table border='1'>"
        "<tr><th>device (on-demand)</th><th>host (continuous)</th></tr>"
        "<tr><td>jax.profiler capture: "
        f"<code>POST /debug/profile{qs}{amp}seconds=N</code> "
        f"(<a href='/debug/profile{qs}'>status</a>); view the trace dir "
        "in tensorboard</td>"
        f"<td><a href='/debug/stacks.json{qs}'>stack summary</a> · "
        f"<a href='/debug/stacks.json{qs}{amp}format=speedscope'>"
        "speedscope</a> · "
        f"<a href='/debug/stacks.json{qs}{amp}format=collapsed'>"
        "collapsed</a> (first click arms the sampler; see also "
        "<code>pio profile --stacks</code>)</td></tr></table>"
    )


def create_dashboard_app(
    storage: StorageRuntime | None = None,
    access_key: str | None = None,
    quality: QualityMonitor | None = None,
    trace_sources: list[str] | None = None,
    fleet_url: str | None = None,
    serving_url: str | None = None,
) -> HTTPApp:
    """``access_key`` gates every route (Dashboard.scala:47 mixes in
    KeyAuthentication); TLS comes from the AppServer layer below.

    ``trace_sources`` (default: ``PIO_TRACE_SOURCES``, comma-separated base
    URLs) names the other daemons' ``/spans.json`` endpoints the
    ``/trace/<id>`` waterfall assembles across — unset, the waterfall shows
    this process's fragments only (still useful for a `pio deploy` whose
    embedded servers share one store).

    ``fleet_url`` (default: ``PIO_FLEET_URL``) names a fleet router whose
    ``/fleet.json`` renders as the Fleet panel — replica membership,
    ejections, and per-replica capacity at a glance.

    ``serving_url`` (default: ``PIO_SERVING_URL``) names a prediction
    replica whose ``/tenants.json`` renders as the Tenants panel — one
    row per resident tenant with SLO state, quota burn, resident HBM
    bytes, and degraded reasons (docs/robustness.md#multi-tenancy)."""
    storage = storage or get_storage()
    app = HTTPApp("dashboard", access_key=access_key)
    quality = quality or default_quality()
    if trace_sources is None:
        trace_sources = [
            u.strip()
            for u in os.environ.get("PIO_TRACE_SOURCES", "").split(",")
            if u.strip()
        ]
    if fleet_url is None:
        fleet_url = os.environ.get("PIO_FLEET_URL") or None
    if serving_url is None:
        serving_url = os.environ.get("PIO_SERVING_URL") or None

    def _metadata_ready() -> bool:
        storage.evaluation_instances().get_completed()
        return True

    # the dashboard runs its own watch loop over the process registry and
    # reads the SAME incident directory the serving process writes (a
    # co-located `pio deploy`'s bundles list here with zero config);
    # PIO_ALERTS=0 disables it like everywhere else
    from predictionio_tpu.obs.alerts import AlertEvaluator
    from predictionio_tpu.obs.incident import IncidentRecorder

    alerts_on = os.environ.get("PIO_ALERTS", "1").lower() not in (
        "0", "off", "false", "no",
    )
    incidents = IncidentRecorder(app=app) if alerts_on else None
    alerts = (
        AlertEvaluator(app=app, incidents=incidents) if alerts_on else None
    )

    # app-level access_key (when set) gates these; /healthz stays public
    add_observability_routes(
        app,
        readiness={"metadata_store": _metadata_ready},
        quality=quality,
        alerts=alerts,
        incidents=incidents,
    )
    # started by AppServer when the dashboard actually serves (app
    # construction stays thread-free — the httpd.AppServer contract)
    app.alerts_autostart = alerts is not None

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        # rendered before the page body: _quality_html refreshes the
        # quality gauges and advances the sparkline ring (see its
        # docstring), so the panels self-populate with CURRENT values even
        # with no external Prometheus scraper
        quality_html = _quality_html(quality, REGISTRY)
        instances = storage.evaluation_instances().get_completed()
        rows = "".join(
            f"<tr><td><a href='/engine_instances/{html.escape(i.id)}'>"
            f"{html.escape(i.id)}</a></td>"
            f"<td>{html.escape(i.evaluation_class)}</td>"
            f"<td>{html.escape(i.start_time.isoformat())}</td>"
            f"<td>{html.escape(i.end_time.isoformat())}</td>"
            f"<td>{html.escape(i.evaluator_results or '')}</td></tr>"
            for i in instances
        )
        return Response(
            200,
            "<html><head><title>PredictionIO-TPU Dashboard</title></head><body>"
            "<h1>Completed evaluations</h1>"
            "<table border='1'><tr><th>id</th><th>evaluation</th>"
            f"<th>started</th><th>finished</th><th>result</th></tr>{rows}"
            f"</table>{_health_html(app)}"
            f"{_alerts_html(app, fleet_url=fleet_url, access_key=access_key)}"
            f"{_capacity_html(app)}"
            + (
                _fleet_html(fleet_url, access_key=access_key)
                if fleet_url
                else ""
            )
            + (
                _tenants_html(serving_url, access_key=access_key)
                if serving_url
                else ""
            )
            + f"{quality_html}"
            f"{_efficiency_html(REGISTRY)}"
            f"{_profiling_html(access_key=access_key)}"
            f"{_traces_table_html(access_key=access_key)}"
            f"{_metrics_table_html(REGISTRY)}</body></html>",
        )

    @app.route("GET", "/trace/(?P<tid>[^/]+)")
    def trace_waterfall(req: Request) -> Response:
        # the assembled cross-process view the Recent-traces rows link to:
        # local fragments + every configured daemon's /spans.json, merged
        # into per-process lanes (dead daemons cost their fragments only)
        tid = req.params["tid"]
        try:
            # short per-source timeout: this blocks a dashboard serving
            # thread, and fetches run concurrently, so a dead daemon in
            # trace_sources costs one bounded wait — not 10 s per corpse
            tl = collect_trace(
                tid,
                urls=trace_sources,
                include_local=True,
                access_key=access_key,
                timeout=3.0,
            )
        except TraceAssemblyError as e:
            return error_response(404, str(e))
        if req.query.get("format") == "perfetto":
            return Response(
                200,
                json.dumps(tl.to_chrome_trace()),
                content_type="application/json",
            )
        return Response(
            200,
            "<html><head><title>Trace "
            f"{html.escape(tid)}</title></head><body>"
            + _waterfall_html(tl, access_key=access_key)
            + "</body></html>",
        )

    @app.route("GET", "/engine_instances/(?P<iid>[^/]+)")
    def detail(req: Request) -> Response:
        inst = storage.evaluation_instances().get(req.params["iid"])
        if inst is None:
            return error_response(404, "Not Found")
        return Response(
            200,
            f"<html><body><h1>Evaluation {html.escape(inst.id)}</h1>"
            f"{inst.evaluator_results_html or '<p>(no results)</p>'}"
            "</body></html>",
        )

    @app.route("GET", "/engine_instances/(?P<iid>[^/]+)/evaluator_results\\.json")
    def detail_json(req: Request) -> Response:
        inst = storage.evaluation_instances().get(req.params["iid"])
        if inst is None:
            return error_response(404, "Not Found")
        return Response(
            200, inst.evaluator_results_json or "{}", content_type="application/json"
        )

    return app


def create_dashboard_server(
    host: str = "0.0.0.0",
    port: int = 9000,
    storage: StorageRuntime | None = None,
    access_key: str | None = None,
    ssl_certfile: str | None = None,
    ssl_keyfile: str | None = None,
) -> AppServer:
    return AppServer(
        create_dashboard_app(storage, access_key=access_key),
        host,
        port,
        ssl_certfile=ssl_certfile,
        ssl_keyfile=ssl_keyfile,
    )
