"""Remote storage daemon — the server-grade networked storage backend.

The reference's production deployments point all three repositories at
networked stores: Elasticsearch serves metadata + events
(storage/elasticsearch/.../ESLEvents.scala:41, ESPEvents.scala:42 — a REST
server owning the data, many client processes), HBase serves events, HDFS
serves models.  This daemon is the TPU-native analog of that *role*: one
process owns the storage root (sqlite metadata + entity-hash-sharded
parquet event log + blob model store) and exposes every DAO contract from
``data/storage/base.py`` over HTTP, so any number of trainer / event-server
/ prediction-server processes on other hosts share one storage service.

Wire protocol: JSON for metadata and row-at-a-time events (the LEvents
side), the PIOF1 binary columnar codec (``data/storage/frame_codec.py``)
for bulk EventFrame scans (the PEvents side) — shard-addressable so
multi-host trainers can each pull their entity-hash range exactly like
``ParquetPEvents.iter_shards`` does locally (the HBEventsUtil.scala:83
row-key partitioning idea, served remotely).

Auth mirrors the dashboard/admin model (KeyAuthentication.scala:33): one
access key gates every route when configured.  TLS comes from AppServer's
PIO_SSL_CERTFILE/KEYFILE support.

Start via ``pio storageserver --port 7072 --root /data/pio`` or embed with
``create_storage_app`` / ``StorageServer`` (tests run it in-process).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np

from predictionio_tpu.data.event import Event, EventValidationError
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.config import StorageConfig, StorageRuntime
from predictionio_tpu.data.storage.base import concat_frames as _concat_frames
from predictionio_tpu.data.storage.frame_codec import decode_frame, encode_frame
from predictionio_tpu.data.storage.remote_backend import (
    engine_instance_from_dict,
    engine_instance_to_dict,
    evaluation_instance_from_dict,
    evaluation_instance_to_dict,
    filter_from_dict,
)
from predictionio_tpu.server.httpd import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    error_response,
    json_response,
)


def _req_filter(req: Request) -> base.EventFilter | None:
    raw = req.query.get("filter")
    return filter_from_dict(json.loads(raw)) if raw else None


def _chan(req: Request) -> int | None:
    c = req.query.get("channel")
    return int(c) if c else None


# ---------------------------------------------------------------------------
# The app
# ---------------------------------------------------------------------------


def create_storage_app(
    runtime: StorageRuntime, access_key: str | None = None
) -> HTTPApp:
    from predictionio_tpu.obs.http import add_observability_routes

    app = HTTPApp("storage-server", access_key=access_key)
    rt = runtime

    def _metadata_ready() -> bool:
        rt.access_keys().get("__readyz_probe__")
        return True

    add_observability_routes(app, readiness={"metadata_store": _metadata_ready})

    @app.route("GET", r"/v1/ping")
    def ping(req: Request) -> Response:
        return json_response(200, {"status": "alive", "service": "storage"})

    # -- apps ----------------------------------------------------------------
    @app.route("POST", r"/v1/apps")
    def apps_insert(req: Request) -> Response:
        d = req.json()
        new_id = rt.apps().insert(
            base.App(
                id=int(d.get("id", 0)),
                name=d["name"],
                description=d.get("description"),
            )
        )
        return json_response(200, {"id": new_id})

    @app.route("GET", r"/v1/apps")
    def apps_all(req: Request) -> Response:
        return json_response(
            200, [dataclasses.asdict(a) for a in rt.apps().get_all()]
        )

    @app.route("GET", r"/v1/apps/id/(?P<id>\d+)")
    def apps_get(req: Request) -> Response:
        a = rt.apps().get(int(req.params["id"]))
        if a is None:
            return error_response(404, "app not found")
        return json_response(200, dataclasses.asdict(a))

    @app.route("GET", r"/v1/apps/name/(?P<name>[^/]+)")
    def apps_get_by_name(req: Request) -> Response:
        a = rt.apps().get_by_name(req.params["name"])
        if a is None:
            return error_response(404, "app not found")
        return json_response(200, dataclasses.asdict(a))

    @app.route("PUT", r"/v1/apps/id/(?P<id>\d+)")
    def apps_update(req: Request) -> Response:
        d = req.json()
        ok = rt.apps().update(
            base.App(
                id=int(req.params["id"]),
                name=d["name"],
                description=d.get("description"),
            )
        )
        return json_response(200, {"ok": ok})

    @app.route("DELETE", r"/v1/apps/id/(?P<id>\d+)")
    def apps_delete(req: Request) -> Response:
        return json_response(200, {"ok": rt.apps().delete(int(req.params["id"]))})

    # -- access keys ---------------------------------------------------------
    @app.route("POST", r"/v1/accesskeys")
    def keys_insert(req: Request) -> Response:
        d = req.json()
        key = rt.access_keys().insert(
            base.AccessKey(
                key=d.get("key", ""),
                appid=int(d["appid"]),
                events=tuple(d.get("events", ())),
            )
        )
        return json_response(200, {"key": key})

    @app.route("GET", r"/v1/accesskeys")
    def keys_all(req: Request) -> Response:
        appid = req.query.get("appid")
        keys = (
            rt.access_keys().get_by_appid(int(appid))
            if appid
            else rt.access_keys().get_all()
        )
        return json_response(
            200,
            [
                {"key": k.key, "appid": k.appid, "events": list(k.events)}
                for k in keys
            ],
        )

    @app.route("GET", r"/v1/accesskeys/(?P<key>[^/]+)")
    def keys_get(req: Request) -> Response:
        k = rt.access_keys().get(req.params["key"])
        if k is None:
            return error_response(404, "access key not found")
        return json_response(
            200, {"key": k.key, "appid": k.appid, "events": list(k.events)}
        )

    @app.route("PUT", r"/v1/accesskeys/(?P<key>[^/]+)")
    def keys_update(req: Request) -> Response:
        d = req.json()
        ok = rt.access_keys().update(
            base.AccessKey(
                key=req.params["key"],
                appid=int(d["appid"]),
                events=tuple(d.get("events", ())),
            )
        )
        return json_response(200, {"ok": ok})

    @app.route("DELETE", r"/v1/accesskeys/(?P<key>[^/]+)")
    def keys_delete(req: Request) -> Response:
        return json_response(200, {"ok": rt.access_keys().delete(req.params["key"])})

    # -- channels ------------------------------------------------------------
    @app.route("POST", r"/v1/channels")
    def chan_insert(req: Request) -> Response:
        d = req.json()
        try:
            ch = base.Channel(
                id=int(d.get("id", 0)), name=d["name"], appid=int(d["appid"])
            )
        except ValueError as e:
            return error_response(400, str(e))
        return json_response(200, {"id": rt.channels().insert(ch)})

    @app.route("GET", r"/v1/channels")
    def chan_by_app(req: Request) -> Response:
        chans = rt.channels().get_by_appid(int(req.query.get("appid", 0)))
        return json_response(200, [dataclasses.asdict(c) for c in chans])

    @app.route("GET", r"/v1/channels/(?P<id>\d+)")
    def chan_get(req: Request) -> Response:
        c = rt.channels().get(int(req.params["id"]))
        if c is None:
            return error_response(404, "channel not found")
        return json_response(200, dataclasses.asdict(c))

    @app.route("DELETE", r"/v1/channels/(?P<id>\d+)")
    def chan_delete(req: Request) -> Response:
        return json_response(
            200, {"ok": rt.channels().delete(int(req.params["id"]))}
        )

    # -- engine / evaluation instances --------------------------------------
    @app.route("POST", r"/v1/engine_instances")
    def ei_insert(req: Request) -> Response:
        i = engine_instance_from_dict(req.json())
        return json_response(200, {"id": rt.engine_instances().insert(i)})

    @app.route("GET", r"/v1/engine_instances")
    def ei_query(req: Request) -> Response:
        q = req.query
        dao = rt.engine_instances()
        if "engine_id" in q:
            args = (q["engine_id"], q.get("engine_version", ""), q.get("engine_variant", ""))
            if q.get("latest"):
                i = dao.get_latest_completed(*args)
                return json_response(
                    200, [engine_instance_to_dict(i)] if i else []
                )
            rows = dao.get_completed(*args)
        else:
            rows = dao.get_all()
        return json_response(200, [engine_instance_to_dict(i) for i in rows])

    @app.route("GET", r"/v1/engine_instances/(?P<id>[^/]+)")
    def ei_get(req: Request) -> Response:
        i = rt.engine_instances().get(req.params["id"])
        if i is None:
            return error_response(404, "engine instance not found")
        return json_response(200, engine_instance_to_dict(i))

    @app.route("PUT", r"/v1/engine_instances/(?P<id>[^/]+)")
    def ei_update(req: Request) -> Response:
        i = engine_instance_from_dict(req.json())
        return json_response(200, {"ok": rt.engine_instances().update(i)})

    @app.route("DELETE", r"/v1/engine_instances/(?P<id>[^/]+)")
    def ei_delete(req: Request) -> Response:
        return json_response(
            200, {"ok": rt.engine_instances().delete(req.params["id"])}
        )

    @app.route("POST", r"/v1/evaluation_instances")
    def vi_insert(req: Request) -> Response:
        i = evaluation_instance_from_dict(req.json())
        return json_response(200, {"id": rt.evaluation_instances().insert(i)})

    @app.route("GET", r"/v1/evaluation_instances")
    def vi_query(req: Request) -> Response:
        dao = rt.evaluation_instances()
        rows = dao.get_completed() if req.query.get("completed") else dao.get_all()
        return json_response(200, [evaluation_instance_to_dict(i) for i in rows])

    @app.route("GET", r"/v1/evaluation_instances/(?P<id>[^/]+)")
    def vi_get(req: Request) -> Response:
        i = rt.evaluation_instances().get(req.params["id"])
        if i is None:
            return error_response(404, "evaluation instance not found")
        return json_response(200, evaluation_instance_to_dict(i))

    @app.route("PUT", r"/v1/evaluation_instances/(?P<id>[^/]+)")
    def vi_update(req: Request) -> Response:
        i = evaluation_instance_from_dict(req.json())
        return json_response(200, {"ok": rt.evaluation_instances().update(i)})

    @app.route("DELETE", r"/v1/evaluation_instances/(?P<id>[^/]+)")
    def vi_delete(req: Request) -> Response:
        return json_response(
            200, {"ok": rt.evaluation_instances().delete(req.params["id"])}
        )

    # -- models (blob store; multipart maps onto keyed blobs client-side) ----
    @app.route("PUT", r"/v1/models/(?P<id>.+)")
    def models_put(req: Request) -> Response:
        rt.models().insert(req.params["id"], req.body)
        return json_response(200, {"ok": True})

    @app.route("GET", r"/v1/models/(?P<id>.+)")
    def models_get(req: Request) -> Response:
        blob = rt.models().get(req.params["id"])
        if blob is None:
            return error_response(404, "model not found")
        return Response(200, blob, content_type="application/octet-stream")

    @app.route("DELETE", r"/v1/models/(?P<id>.+)")
    def models_delete(req: Request) -> Response:
        return json_response(200, {"ok": rt.models().delete(req.params["id"])})

    # -- LEvents -------------------------------------------------------------
    @app.route("POST", r"/v1/apps/(?P<app>\d+)/init")
    def ev_init(req: Request) -> Response:
        ok = rt.l_events().init(int(req.params["app"]), _chan(req))
        return json_response(200, {"ok": ok})

    @app.route("POST", r"/v1/apps/(?P<app>\d+)/remove")
    def ev_remove(req: Request) -> Response:
        ok = rt.l_events().remove(int(req.params["app"]), _chan(req))
        return json_response(200, {"ok": ok})

    @app.route("POST", r"/v1/apps/(?P<app>\d+)/events")
    def ev_insert(req: Request) -> Response:
        try:
            events = [Event.from_api_dict(d) for d in req.json()]
        except (EventValidationError, TypeError, KeyError) as e:
            return error_response(400, f"invalid event: {e}")
        ids = rt.l_events().insert_batch(
            events, int(req.params["app"]), _chan(req)
        )
        return json_response(200, {"ids": ids})

    @app.route("GET", r"/v1/apps/(?P<app>\d+)/events")
    def ev_find(req: Request) -> Response:
        events = rt.l_events().find(
            int(req.params["app"]), _chan(req), _req_filter(req)
        )
        return json_response(200, [e.to_api_dict() for e in events])

    @app.route("GET", r"/v1/apps/(?P<app>\d+)/events/(?P<eid>[^/]+)")
    def ev_get(req: Request) -> Response:
        e = rt.l_events().get(req.params["eid"], int(req.params["app"]), _chan(req))
        if e is None:
            return error_response(404, "event not found")
        return json_response(200, e.to_api_dict())

    @app.route("DELETE", r"/v1/apps/(?P<app>\d+)/events/(?P<eid>[^/]+)")
    def ev_delete(req: Request) -> Response:
        ok = rt.l_events().delete(
            req.params["eid"], int(req.params["app"]), _chan(req)
        )
        return json_response(200, {"ok": ok})

    # -- PEvents (bulk columnar, shard-addressable) --------------------------
    @app.route("GET", r"/v1/apps/(?P<app>\d+)/shards")
    def fr_shards(req: Request) -> Response:
        """The shard count the scan protocol is keyed on — the APP's actual
        layout via the PEvents.n_shards contract (a parquet app dir records
        its n_shards at creation, which may differ from the daemon's
        default)."""
        n = rt.p_events().n_shards(int(req.params["app"]), _chan(req))
        return json_response(200, {"n_shards": n})

    @app.route("GET", r"/v1/apps/(?P<app>\d+)/frame")
    def fr_scan(req: Request) -> Response:
        """Bulk scan; ``shards`` (CSV of shard indices) restricts to those
        entity-hash shards in ONE request/scan — SQL-backed stores split a
        single table scan on the host, so a grouped fetch costs one scan
        instead of one per shard."""
        app_id, chan, flt = int(req.params["app"]), _chan(req), _req_filter(req)
        pe = rt.p_events()
        csv = req.query.get("shards")
        if csv is not None:
            want = [int(x) for x in csv.split(",") if x != ""]
            if hasattr(pe, "iter_shards"):
                frames = [
                    f for _, f in pe.iter_shards(app_id, chan, flt, shards=want)
                ]
                frame = _concat_frames(frames)
            else:
                # The base PEvents contract doesn't require iter_shards.
                # Clients (RemotePEvents' singleton fast path) trust that a
                # shard-restricted response IS the requested shards, so a
                # full-scan answer here would hand every worker the whole
                # event log — silent row duplication in multi-process
                # training.  Re-split server-side with the shared hash.
                from predictionio_tpu.data.storage.base import frame_shard_of

                frame = pe.find(app_id, chan, flt)
                shard_of = frame_shard_of(
                    frame.entity_type, frame.entity_id,
                    pe.n_shards(app_id, chan),
                )
                frame = frame.take(np.isin(shard_of, want))
        else:
            frame = pe.find(app_id, chan, flt)
        return Response(
            200, encode_frame(frame), content_type="application/x-pio-frame"
        )

    @app.route("POST", r"/v1/apps/(?P<app>\d+)/frame")
    def fr_write(req: Request) -> Response:
        frame = decode_frame(req.body)
        rt.p_events().write(frame, int(req.params["app"]), _chan(req))
        return json_response(200, {"ok": True, "rows": len(frame)})

    @app.route("POST", r"/v1/apps/(?P<app>\d+)/compact")
    def fr_compact(req: Request) -> Response:
        pe = rt.p_events()
        fn = getattr(pe, "compact", None)
        if fn is None:  # SQL stores rewrite in place; nothing to fold
            return json_response(200, {"supported": False, "rows": 0})
        rows = fn(int(req.params["app"]), _chan(req))
        return json_response(200, {"supported": True, "rows": rows})

    @app.route("GET", r"/v1/apps/(?P<app>\d+)/eventstore_status")
    def fr_status(req: Request) -> Response:
        pe = rt.p_events()
        fn = getattr(pe, "status", None)
        if fn is None:  # SQL stores have no segment layout to report
            return json_response(200, {"supported": False})
        return json_response(200, fn(int(req.params["app"]), _chan(req)))

    @app.route("POST", r"/eventstore/compact")
    def eventstore_compact(req: Request) -> Response:
        """Fold every app on this daemon now (idempotent; the background
        compactor also runs on its own cadence)."""
        pe = rt.p_events()
        fn = getattr(pe, "compact", None)
        if fn is None:
            return json_response(200, {"supported": False, "apps": 0, "rows": 0})
        client = getattr(getattr(pe, "store", None), "client", None)
        from predictionio_tpu.data.storage.compactor import Compactor

        comp = getattr(app, "compactor", None) or (
            Compactor(client) if client is not None else None
        )
        if comp is None:
            return json_response(200, {"supported": False, "apps": 0, "rows": 0})
        apps = 0
        rows = 0
        for app_id, channel_id in comp.app_keys():
            rows += comp.store.compact(app_id, channel_id)
            apps += 1
        return json_response(
            200, {"supported": True, "apps": apps, "rows": rows}
        )

    @app.route("GET", r"/eventstore\.json")
    def eventstore_status(req: Request) -> Response:
        """Aggregate segment/compaction status across every app on this
        daemon — what ``pio eventstore status --url`` and the ``pio
        status`` backlog WARNING read."""
        comp = getattr(app, "compactor", None)
        if comp is not None:
            return json_response(200, comp.status())
        # no background compactor: synthesize the same shape on demand
        pe = rt.p_events()
        client = getattr(getattr(pe, "store", None), "client", None)
        if client is None:
            return json_response(200, {"supported": False, "apps": []})
        from predictionio_tpu.data.storage.compactor import (
            CompactionPolicy,
            Compactor,
        )

        return json_response(
            200, Compactor(client, CompactionPolicy.from_env()).status()
        )

    @app.route("POST", r"/v1/apps/(?P<app>\d+)/frame_delete")
    def fr_delete(req: Request) -> Response:
        ids = req.json().get("ids", [])
        rt.p_events().delete(ids, int(req.params["app"]), _chan(req))
        return json_response(200, {"ok": True})

    return app


def runtime_for_root(root: str | Path, events: str = "parquet") -> StorageRuntime:
    """Self-contained storage topology under one root directory: sqlite
    metadata + models, parquet (default) or sqlite events."""
    root = Path(root)
    env = {"PIO_HOME": str(root)}
    if events == "parquet":
        env |= {
            "PIO_STORAGE_SOURCES_PQ_TYPE": "parquet",
            "PIO_STORAGE_SOURCES_PQ_PATH": str(root / "events_parquet"),
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "PQ",
        }
    return StorageRuntime(StorageConfig.from_env(env))


class StorageServer:
    """Bind-and-serve wrapper (the daemon entry).

    With ``compaction=True`` (the default for parquet event stores) the
    daemon owns a background :class:`Compactor` that folds the write-hot
    head into sorted compacted segments on a watermark cadence — the
    HBase major-compaction role, continuous instead of operator-driven.
    """

    def __init__(
        self,
        root: str | Path,
        host: str = "0.0.0.0",
        port: int = 7072,
        access_key: str | None = None,
        events: str = "parquet",
        compaction: bool = True,
        compact_interval_s: float | None = None,
    ):
        self.runtime = runtime_for_root(root, events=events)
        self.app = create_storage_app(self.runtime, access_key=access_key)
        self.compactor = None
        self._owner_lock = None
        if events == "parquet":
            # advisory ownership of the parquet root for the daemon's
            # lifetime: other processes (CLI local compact) refuse to
            # fold a root whose in-flight-write bookkeeping lives here
            from predictionio_tpu.data.storage.parquet_backend import (
                acquire_root_ownership,
            )

            pe0 = self.runtime.p_events()
            client0 = getattr(getattr(pe0, "store", None), "client", None)
            if client0 is not None:
                self._owner_lock = acquire_root_ownership(client0.root)
                if self._owner_lock is None:
                    import logging

                    logging.getLogger(
                        "predictionio_tpu.server.storage"
                    ).warning(
                        "another process already owns storage root %s; "
                        "two daemons on one root will corrupt compaction",
                        client0.root,
                    )
        if compaction and events == "parquet":
            from predictionio_tpu.data.storage.compactor import (
                CompactionPolicy,
                Compactor,
            )

            pe = self.runtime.p_events()
            client = getattr(getattr(pe, "store", None), "client", None)
            if client is not None:
                policy = CompactionPolicy.from_env()
                if compact_interval_s is not None:
                    import dataclasses

                    policy = dataclasses.replace(
                        policy, interval_s=compact_interval_s
                    )
                self.compactor = Compactor(client, policy)
                self.app.compactor = self.compactor
        self.server = AppServer(self.app, host=host, port=port)
        self.host, self.port = self.server.host, self.server.port

    def start_background(self) -> "StorageServer":
        if self.compactor is not None:
            self.compactor.start()
        self.server.start_background()
        return self

    def serve_forever(self) -> None:
        if self.compactor is not None:
            self.compactor.start()
        self.server.serve_forever()

    def shutdown(self) -> None:
        if self.compactor is not None:
            self.compactor.stop()
        self.server.shutdown()
        self.runtime.close()
        if self._owner_lock is not None:
            try:
                self._owner_lock.close()  # releases the flock
            except OSError:
                pass
            self._owner_lock = None
