"""Admin REST API (:7071) — app/access-key management over HTTP.

Route parity with tools/admin/AdminAPI.scala:45-109 + CommandClient.scala:61:

  GET    /                      {"status": "alive"}
  GET    /cmd/app               list apps
  POST   /cmd/app               create app {"name": ..., ["description"]}
  DELETE /cmd/app/<name>        delete app + keys + events
  GET    /cmd/app/<name>        show app
  DELETE /cmd/app/<name>/data   wipe the app's events
"""

from __future__ import annotations

from predictionio_tpu.data.storage.config import StorageRuntime, get_storage
from predictionio_tpu.obs.http import add_observability_routes
from predictionio_tpu.server.httpd import (
    AppServer,
    HTTPApp,
    Request,
    Response,
    error_response,
    json_response,
)
from predictionio_tpu.tools.commands import (
    AppDescription,
    CommandError,
    app_data_delete,
    app_delete,
    app_list,
    app_new,
    app_show,
)


def create_admin_app(
    storage: StorageRuntime | None = None, access_key: str | None = None
) -> HTTPApp:
    """``access_key`` gates every route (the dashboard's KeyAuthentication
    applied to the admin surface); TLS comes from the AppServer layer."""
    storage = storage or get_storage()
    app = HTTPApp("adminserver", access_key=access_key)

    def _metadata_ready() -> bool:
        storage.access_keys().get("__readyz_probe__")
        return True

    # app-level access_key (when set) gates these; /healthz stays public
    add_observability_routes(app, readiness={"metadata_store": _metadata_ready})

    def describe(d: AppDescription) -> dict:
        return d.to_json_dict()

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        return json_response(200, {"status": "alive"})

    @app.route("GET", "/cmd/app")
    def list_apps(req: Request) -> Response:
        return json_response(200, [describe(d) for d in app_list(storage)])

    @app.route("POST", "/cmd/app")
    def new_app(req: Request) -> Response:
        try:
            payload = req.json() or {}
            name = payload["name"]
        except Exception:
            return error_response(400, "body must be JSON with a 'name' field")
        try:
            d = app_new(
                storage,
                name,
                description=payload.get("description", ""),
                access_key=payload.get("accessKey"),
            )
        except CommandError as e:
            return error_response(409, str(e))
        return json_response(201, describe(d))

    @app.route("GET", "/cmd/app/(?P<name>[^/]+)")
    def show_app(req: Request) -> Response:
        try:
            return json_response(200, describe(app_show(storage, req.params["name"])))
        except CommandError as e:
            return error_response(404, str(e))

    @app.route("DELETE", "/cmd/app/(?P<name>[^/]+)")
    def delete_app(req: Request) -> Response:
        try:
            app_delete(storage, req.params["name"])
        except CommandError as e:
            return error_response(404, str(e))
        return json_response(200, {"message": f"App {req.params['name']} deleted"})

    @app.route("DELETE", "/cmd/app/(?P<name>[^/]+)/data")
    def delete_data(req: Request) -> Response:
        try:
            app_data_delete(storage, req.params["name"])
        except CommandError as e:
            return error_response(404, str(e))
        return json_response(200, {"message": "Data deleted"})

    return app


def create_admin_server(
    host: str = "0.0.0.0",
    port: int = 7071,
    storage: StorageRuntime | None = None,
    access_key: str | None = None,
    ssl_certfile: str | None = None,
    ssl_keyfile: str | None = None,
) -> AppServer:
    return AppServer(
        create_admin_app(storage, access_key=access_key),
        host,
        port,
        ssl_certfile=ssl_certfile,
        ssl_keyfile=ssl_keyfile,
    )
