"""Minimal threaded HTTP routing layer shared by all servers.

The stdlib replacement for the reference's akka-http stack
(common/.../akkahttpjson4s/Json4sSupport.scala + the per-server route DSLs):
a tiny Route/Request/Response model on top of ``http.server``.  Handlers are
plain functions so route logic is unit-testable without sockets (the way the
reference tests routes with akka-http TestKit, EventServiceSpec.scala:27).

Request concurrency comes from ``ThreadingHTTPServer`` (thread per
connection); jit-compiled predict paths are already thread-safe on the JAX
side, and storage DAOs are connection-per-thread.
"""

from __future__ import annotations

import json
import re
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Mapping
from urllib.parse import parse_qs, unquote, urlsplit

from predictionio_tpu.obs.logging import (
    REQUEST_ID_HEADER,
    new_request_id,
    reset_request_context,
    set_request_context,
)
from predictionio_tpu.resilience import LoadShed
from predictionio_tpu.resilience.breaker import CircuitOpen
from predictionio_tpu.resilience.deadline import (
    DEADLINE_HEADER,
    DeadlineExceeded,
    deadline_scope,
    parse_budget,
)


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: Mapping[str, str]
    body: bytes = b""
    #: named groups captured from the route pattern
    params: dict[str, str] = field(default_factory=dict)

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body.decode("utf-8"))

    def form(self) -> dict[str, str]:
        data = parse_qs(self.body.decode("utf-8"), keep_blank_values=True)
        return {k: v[0] for k, v in data.items()}


@dataclass
class Response:
    status: int = 200
    body: Any = None  # dict/list -> JSON; str -> text/html; bytes raw
    content_type: str | None = None
    headers: dict[str, str] = field(default_factory=dict)

    def encoded(self) -> tuple[bytes, str]:
        # memoized: the observability layer measures response_bytes and the
        # front end then encodes for the wire — JSON-serializing a large
        # prediction body twice per request would be measurable
        cached = getattr(self, "_encoded_cache", None)
        if cached is not None:
            return cached
        if isinstance(self.body, bytes):
            out = self.body, self.content_type or "application/octet-stream"
        elif isinstance(self.body, str):
            out = self.body.encode("utf-8"), self.content_type or (
                "text/html; charset=utf-8"
            )
        else:
            out = (
                json.dumps(self.body).encode("utf-8"),
                self.content_type or "application/json; charset=utf-8",
            )
        self._encoded_cache = out
        return out


Handler = Callable[[Request], Response]


def unquote_groups(m: re.Match) -> dict[str, str]:
    """Percent-decode captured route params AFTER matching.  Matching runs
    on the still-quoted path so a value containing an encoded '/' (%2F)
    stays one segment — unquoting first would turn it into a path
    separator and 404 every [^/]+ route for such names."""
    return {
        k: (unquote(v) if v is not None else v) for k, v in m.groupdict().items()
    }


def json_response(status: int, body: Any) -> Response:
    return Response(status=status, body=body)


def error_response(status: int, message: str) -> Response:
    return Response(status=status, body={"message": message})


def shed_response(message: str, retry_after_s: float = 1.0) -> Response:
    """503 with a ``Retry-After`` hint — the load-shedding answer.  A shed
    is cheap to produce and honest to the client: back off and retry,
    rather than queue behind a saturated server until you time out."""
    import math

    resp = error_response(503, message)
    resp.headers["Retry-After"] = str(max(int(math.ceil(retry_after_s)), 1))
    return resp


def exception_response(e: Exception) -> Response:
    """Map a handler exception to its HTTP shape: deadline errors are 504,
    shed/breaker rejections are 503 + Retry-After, anything else is the
    legacy 500.  Shared by both front ends and ``HTTPApp.handle`` so a
    sync handler raising ``DeadlineExceeded`` answers the same as an async
    one."""
    if isinstance(e, DeadlineExceeded):
        return error_response(504, f"deadline exceeded: {e}")
    if isinstance(e, (LoadShed, CircuitOpen)):
        return shed_response(str(e), getattr(e, "retry_after_s", 1.0))
    return error_response(500, f"{type(e).__name__}: {e}")


def request_budget(app: "HTTPApp", req: Request) -> float | None:
    """The request's time budget in seconds: the ``X-Pio-Deadline`` header
    when present (malformed values are ignored, not 500s), else the
    request's tenant's deadline default (stamped on ``req`` by the
    admission gate), else the server's ``default_deadline_s`` (None = no
    deadline)."""
    budget = parse_budget(header_get(req.headers, DEADLINE_HEADER))
    if budget is None:
        tenant = getattr(req, "tenant", None)
        if tenant is not None and tenant.default_deadline_s is not None:
            budget = tenant.default_deadline_s
        else:
            budget = getattr(app, "default_deadline_s", None)
    return budget


def _record_slo_failure(app: "HTTPApp") -> None:
    """Admission rejections (sheds, expired budgets) are user-visible
    failures: they must burn SLO error budget so overload pages someone."""
    slo = getattr(app, "slo", None)
    if slo is not None:
        slo.record(False, 0.0)


class _CompositeRelease:
    """Release both the server-wide admission slot and the per-tenant one
    in one ``release()`` — what ``admit_request`` hands the front ends
    when a tenant registry is configured."""

    __slots__ = ("_parts",)

    def __init__(self, *parts):
        self._parts = [p for p in parts if p is not None]

    def release(self) -> None:
        for p in self._parts:
            p.release()


def admit_request(app: "HTTPApp", req: Request | None = None):
    """Admission gate shared by both HTTP front ends: the server-wide
    in-flight cap, then (when ``app.tenants`` is a TenantRegistry and the
    request is given) the per-tenant gate — quota token bucket and
    per-tenant in-flight cap, shed with ``reason=tenant_quota`` /
    ``tenant_inflight`` BEFORE the query reaches the MicroBatcher.

    Returns ``(releaser, None)`` when admitted — ``releaser`` is what the
    caller must ``release()`` in its finally (None when no cap is
    configured) — or ``(None, 503-shed-response)`` when rejected: past a
    cap, shedding NOW is cheaper for everyone than queueing into a
    timeout."""
    adm = getattr(app, "admission", None)
    if adm is not None and not adm.try_acquire():
        _record_slo_failure(app)
        return None, shed_response(
            "server over capacity; retry later", adm.retry_after_s
        )
    tenants = getattr(app, "tenants", None)
    if tenants is None or req is None:
        return adm, None
    tenant, releaser, shed = tenants.gate(req)
    if shed is not None:
        # the tenant's own SLO already burned inside gate(); the victim is
        # contained — the server-wide SLO does NOT burn for a per-tenant
        # shed, so one flooding tenant cannot page the whole replica
        if adm is not None:
            adm.release()
        return None, shed
    return _CompositeRelease(adm, releaser), None


def admission_expired_response(app: "HTTPApp") -> Response:
    """504 for a request whose budget was already gone at admission —
    answering now beats doing work nobody will read."""
    _record_slo_failure(app)
    return error_response(504, "deadline expired at admission")


def header_get(headers: Mapping[str, str] | None, name: str) -> str:
    """Case-tolerant header lookup: the threaded server hands out an
    email.Message (case-insensitive), the aio front end a lower-cased dict,
    and tests pass plain dicts."""
    if not headers:
        return ""
    return headers.get(name) or headers.get(name.lower()) or ""


def presented_key(req: Request) -> str:
    """The access key a request presents: ``Authorization: Bearer <key>``
    preferred (doesn't land in proxy/access logs), ``?accessKey=`` kept for
    dashboard-link parity (Dashboard.scala:47)."""
    auth = header_get(req.headers, "Authorization")
    if auth.startswith("Bearer "):
        return auth[len("Bearer "):]
    return req.query.get("accessKey", "")


def key_matches(req: Request, key: str) -> bool:
    """Constant-time comparison of the presented key against ``key`` — the
    ONE credential check every key-gated surface uses (app-level gate and
    the observability routes), so hardening it lands everywhere at once."""
    import hmac

    # bytes, not str: compare_digest raises TypeError on non-ASCII str
    return hmac.compare_digest(
        presented_key(req).encode("utf-8"), key.encode("utf-8")
    )


class HTTPApp:
    """Route table: (method, compiled path regex) -> handler.

    ``access_key``, when set, gates EVERY route behind ``?accessKey=``
    (the KeyAuthentication role, common/.../KeyAuthentication.scala:33, as
    the dashboard/admin servers use it, Dashboard.scala:47).  Servers with
    per-app key auth (event server) leave it unset and authenticate
    per-route instead.
    """

    def __init__(self, name: str = "server", access_key: str | None = None):
        self.name = name
        self.access_key = access_key
        self._routes: list[tuple[str, re.Pattern, Handler]] = []

    def route(self, method: str, pattern: str, public: bool = False):
        """Register a handler; ``pattern`` is a path regex with named groups,
        anchored at both ends.  ``public=True`` exempts the route from the
        app-level ``access_key`` gate (liveness probes: load balancers carry
        no keys)."""
        compiled = re.compile("^" + pattern + "$")

        def deco(fn: Handler) -> Handler:
            if public:
                fn._pio_public = True  # type: ignore[attr-defined]
            self._routes.append((method.upper(), compiled, fn))
            return fn

        return deco

    def _key_ok(self, req: Request) -> bool:
        """Constant-time key check (Bearer header or ?accessKey=)."""
        return key_matches(req, self.access_key)

    def match(self, req: Request) -> tuple[Handler | None, re.Match | None, int]:
        """Resolve a request to (handler, match, status): status is 200 when
        a handler matched, else the 404/405 to answer with.  Shared by both
        HTTP front ends so routing semantics can't drift."""
        path_matched = False
        for method, pattern, fn in self._routes:
            m = pattern.match(req.path)
            if not m:
                continue
            path_matched = True
            if method != req.method:
                continue
            return fn, m, 200
        return None, None, 405 if path_matched else 404

    def auth_error(self, req: Request, fn: Handler | None) -> Response | None:
        """App-level key gate for a resolved handler; public routes bypass
        it.  None means authorized (or no key configured)."""
        if self.access_key is None:
            return None
        if fn is not None and getattr(fn, "_pio_public", False):
            return None
        if self._key_ok(req):
            return None
        return error_response(401, "Invalid accessKey.")

    def handle(self, req: Request) -> Response:
        fn, m, status = self.match(req)
        denied = self.auth_error(req, fn)
        if denied is not None:
            return denied
        if fn is None:
            return error_response(
                status,
                "Method Not Allowed" if status == 405 else "Not Found",
            )
        req.params = unquote_groups(m)
        try:
            return fn(req)
        except Exception as e:  # the exceptionHandler analog
            return exception_response(e)


def observe_request(
    app: HTTPApp, req: Request, call: Callable[[Request], Response]
) -> Response:
    """Request-lifecycle bookkeeping shared by the threaded front end (and
    mirrored in async form by server/aio.py): mint/adopt the request id,
    bind it to the logging context, wrap the handler in an unrecorded root
    span, echo ``X-Pio-Request-Id``, and feed the SLO tracker + flight
    recorder.  Observability/probe paths skip the span + accounting so
    scrapes never pollute the trace ring or the SLO window."""
    from predictionio_tpu.obs.disttrace import (
        TRACE_ID_HEADER,
        adopt_trace_context,
        bind_parent_span,
        reset_parent_span,
    )
    from predictionio_tpu.obs.flight import begin_annotations, end_annotations
    from predictionio_tpu.obs.http import (
        is_observability_path,
        record_request_outcome,
    )
    from predictionio_tpu.obs.provenance import (
        begin_capture,
        end_capture,
        wants_deep,
    )
    from predictionio_tpu.obs.tracing import trace

    rid = header_get(req.headers, REQUEST_ID_HEADER) or new_request_id()
    if is_observability_path(req.path):
        resp = call(req)
        resp.headers.setdefault(REQUEST_ID_HEADER, rid)
        return resp
    adm, shed = admit_request(app, req)
    if shed is not None:
        shed.headers.setdefault(REQUEST_ID_HEADER, rid)
        return shed
    budget = request_budget(app, req)
    # cross-process tracing: adopt the caller's trace id (or start a new
    # trace under this request id) and the parent span this process's root
    # spans should hang under
    tid, parent_span = adopt_trace_context(req.headers, rid)
    tokens = set_request_context(rid, tid)
    ptoken = bind_parent_span(parent_span)
    ann_token = begin_annotations()
    # decision-provenance scope: cheap capture always, deep on X-Pio-Explain
    prov_token = begin_capture(deep=wants_deep(req.headers))
    t0 = time.perf_counter()
    try:
        if budget is not None and budget <= 0:
            resp = admission_expired_response(app)
        else:
            with deadline_scope(budget_s=budget):
                with trace(f"http.{app.name}", record=False) as span:
                    resp = call(req)
                    span.tags = {
                        "method": req.method,
                        "path": req.path,
                        "status": resp.status,
                    }
                resp.headers.setdefault(REQUEST_ID_HEADER, rid)
                try:
                    record_request_outcome(
                        app, req, resp, time.perf_counter() - t0, span
                    )
                except Exception:  # telemetry must never fail the request
                    pass
        resp.headers.setdefault(REQUEST_ID_HEADER, rid)
        resp.headers.setdefault(TRACE_ID_HEADER, tid)
        return resp
    finally:
        if adm is not None:
            adm.release()
        end_capture(prov_token)
        end_annotations(ann_token)
        reset_parent_span(ptoken)
        reset_request_context(tokens)


def _make_handler_class(app: HTTPApp):
    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = f"predictionio-tpu/{app.name}"

        def _dispatch(self, method: str) -> None:
            split = urlsplit(self.path)
            q = parse_qs(split.query, keep_blank_values=True)
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            req = Request(
                method=method,
                path=split.path,
                query={k: v[0] for k, v in q.items()},
                headers=self.headers,
                body=body,
            )
            resp = observe_request(app, req, app.handle)
            payload, ctype = resp.encoded()
            self.send_response(resp.status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            for k, v in resp.headers.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

        def do_DELETE(self):
            self._dispatch("DELETE")

        def do_PUT(self):
            self._dispatch("PUT")

        def log_message(self, fmt, *args):  # quiet by default
            pass

    return _Handler


class AppServer:
    """Bind an HTTPApp on host:port with a background serve thread.

    TLS (the reference's SSLConfiguration/server.conf role,
    common/.../configuration/SSLConfiguration.scala:28) comes from the
    ``PIO_SSL_CERTFILE``/``PIO_SSL_KEYFILE`` env vars or explicit paths —
    PEM files instead of a JKS keystore.
    """

    def __init__(
        self,
        app: HTTPApp,
        host: str = "0.0.0.0",
        port: int = 7070,
        ssl_certfile: str | None = None,
        ssl_keyfile: str | None = None,
    ):
        import os

        self.app = app
        self.httpd = ThreadingHTTPServer((host, port), _make_handler_class(app))
        certfile = ssl_certfile or os.environ.get("PIO_SSL_CERTFILE")
        keyfile = ssl_keyfile or os.environ.get("PIO_SSL_KEYFILE")
        if certfile:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile, keyfile)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True
            )
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def _start_app_daemons(self) -> None:
        """Per-app daemons (the alert evaluator) start when the app starts
        SERVING — constructing an app must stay thread-free so a process
        that builds many (tests, tooling) doesn't accumulate watchers."""
        alerts = getattr(self.app, "alerts", None)
        if alerts is not None and getattr(
            self.app, "alerts_autostart", False
        ):
            alerts.start()

    def start_background(self) -> "AppServer":
        self._start_app_daemons()
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name=f"{self.app.name}-http", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._start_app_daemons()
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        batcher = getattr(self.app, "microbatcher", None)
        if batcher is not None:
            batcher.close()
        alerts = getattr(self.app, "alerts", None)
        if alerts is not None:
            alerts.stop()
