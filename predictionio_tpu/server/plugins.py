"""Server plugin hooks: input blockers, output blockers, sniffers.

Parity with the reference plugin seams (workflow/EngineServerPlugin.scala:24
— outputblocker/outputsniffer; data/api/EventServerPlugin.scala:22 — input
blocker/sniffer; loaded from a classpath scan in
EngineServerPluginContext.scala:49).  Here plugins are plain objects
registered programmatically or resolved from the ``PIO_PLUGINS`` env var
(comma-separated ``pkg.module:attr`` import paths — the Python analog of
dropping jars into plugins/).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from typing import Any, Callable

log = logging.getLogger("predictionio_tpu.plugins")

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"
OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EventServerPlugin:
    """Event-ingest hook: ``process`` may mutate-or-raise (blocker) or just
    observe (sniffer).  ``handle_rest`` (optional) answers the server's
    ``/plugins/<type>/<name>/...`` routes (EventServer.scala:154-206)."""

    plugin_name = "event-plugin"
    plugin_type = INPUT_SNIFFER

    def process(self, app_id: int, channel_id: int | None, event) -> None:
        raise NotImplementedError

    def handle_rest(self, path: str, query: dict) -> Any:
        """Plugin-specific HTTP endpoint; return a JSON-able value."""
        return {"message": f"{self.plugin_name} has no REST handler"}


class EngineServerPlugin:
    """Serving hook: blockers transform (or veto, by raising) the rendered
    prediction; sniffers observe asynchronously.  ``handle_rest`` (optional)
    answers ``/plugins/<type>/<name>/...`` (CreateServer.scala:656-702)."""

    plugin_name = "engine-plugin"
    plugin_type = OUTPUT_SNIFFER

    def process(
        self, engine_instance_id: str, query: Any, prediction: Any
    ) -> Any:
        raise NotImplementedError

    def handle_rest(self, path: str, query: dict) -> Any:
        """Plugin-specific HTTP endpoint; return a JSON-able value."""
        return {"message": f"{self.plugin_name} has no REST handler"}


class PluginContext:
    """Holds registered plugins, split by type.

    Sniffers run on ONE long-lived worker thread draining a queue (the
    plugins-actor analog) so the ingest/serving hot paths never pay
    thread-creation cost and observations stay ordered.
    """

    def __init__(self):
        self._plugins: list[Any] = []
        self._queue: queue.Queue | None = None

    def register(self, plugin: Any) -> None:
        if not isinstance(getattr(plugin, "plugin_type", None), str):
            raise TypeError(
                f"plugin {plugin!r} has no plugin_type attribute"
            )
        self._plugins.append(plugin)

    def of_type(self, plugin_type: str) -> list[Any]:
        return [p for p in self._plugins if p.plugin_type == plugin_type]

    # -- hook runners --------------------------------------------------------
    def process_input(self, app_id: int, channel_id: int | None, event) -> None:
        """Blockers run inline (exception rejects the event); sniffers are
        queued to the worker."""
        for p in self.of_type(INPUT_BLOCKER):
            p.process(app_id, channel_id, event)
        sniffers = self.of_type(INPUT_SNIFFER)
        if sniffers:
            self._enqueue(sniffers, (app_id, channel_id, event))

    def process_output(
        self, engine_instance_id: str, query: Any, prediction: Any
    ) -> Any:
        for p in self.of_type(OUTPUT_BLOCKER):
            prediction = p.process(engine_instance_id, query, prediction)
        sniffers = self.of_type(OUTPUT_SNIFFER)
        if sniffers:
            self._enqueue(sniffers, (engine_instance_id, query, prediction))
        return prediction

    def _enqueue(self, sniffers, args) -> None:
        if self._queue is None:
            self._queue = queue.Queue()
            threading.Thread(
                target=self._drain, name="plugin-sniffers", daemon=True
            ).start()
        self._queue.put((sniffers, args))

    def _drain(self) -> None:
        while True:
            sniffers, args = self._queue.get()
            for p in sniffers:
                try:
                    p.process(*args)
                except Exception:
                    log.exception("sniffer plugin %s failed", p.plugin_name)
            self._queue.task_done()

    def drain_pending(self) -> None:
        """Block until queued sniffer work is processed (tests/shutdown)."""
        if self._queue is not None:
            self._queue.join()

    # -- HTTP introspection (the /plugins* route surface) --------------------
    def descriptions(self) -> dict[str, dict[str, dict]]:
        """{plugin_type: {plugin_name: {class}}} for GET /plugins.json
        (EventServer.scala:154-165, CreateServer.scala:656-668)."""
        out: dict[str, dict[str, dict]] = {}
        for p in self._plugins:
            out.setdefault(p.plugin_type, {})[p.plugin_name] = {
                "class": type(p).__qualname__
            }
        return out

    def find(self, plugin_type: str, plugin_name: str):
        for p in self.of_type(plugin_type):
            if p.plugin_name == plugin_name:
                return p
        return None

    def rest_response(self, plugin_type: str, plugin_name: str,
                      path: str, query: dict):
        """Dispatch a /plugins/<type>/<name>/<path> request to the plugin's
        ``handle_rest``, wrapping the result as an HTTP Response."""
        from predictionio_tpu.server.httpd import (
            Response,
            error_response,
            json_response,
        )

        p = self.find(plugin_type, plugin_name)
        if p is None:
            return error_response(
                404, f"no {plugin_type} plugin named {plugin_name!r}"
            )
        handler = getattr(p, "handle_rest", None)
        if handler is None:
            return error_response(
                404, f"plugin {plugin_name!r} has no REST handler"
            )
        out = handler(path or "/", query)
        return out if isinstance(out, Response) else json_response(200, out)

    @classmethod
    def from_env(cls, env_var: str = "PIO_PLUGINS") -> "PluginContext":
        """Resolve plugin instances/classes/factories from import paths.

        A bad entry is logged and skipped — one misconfigured plugin must
        not poison every request.
        """
        from predictionio_tpu.utils.registry import resolve_import_path

        ctx = cls()
        spec = os.environ.get(env_var, "")
        for path in filter(None, (s.strip() for s in spec.split(","))):
            try:
                obj = resolve_import_path(path)
                if obj is None:
                    raise KeyError(f"import path {path!r} not found")
                if callable(obj) and not isinstance(
                    getattr(obj, "plugin_type", None), str
                ):
                    obj = obj()  # class or factory function
                ctx.register(obj)
            except Exception:
                log.exception("skipping plugin %s", path)
        return ctx
